"""LLMEngine: continuous batching over the paged JAX model.

Mirrors the serving loop the reference drives through vLLM (SURVEY.md §3.1 'HOT LOOP:
continuous batching on accelerator'), built XLA-first:

- exactly two compiled programs after warmup — ``_unified_fn`` (flat mixed batch:
  several sequences' prefill chunks + decode tokens packed into a fixed
  ``max_num_batched_tokens`` budget, the --max-num-batched-tokens analogue) and
  ``_decode_multi_fn`` (fixed slot batch, k fused decode iterations under
  ``lax.scan``) — both static-shaped; the host scheduler packs work into them,
- prefill batches ACROSS sequences: 32 arriving requests chunk-prefill together up
  to the token budget instead of one sequence per step,
- prefill never pays the [N, vocab] logits matmul — only each sequence's last
  hidden row is unembedded,
- automatic prefix caching with chained block hashes + KV events (kv_manager),
- preemption by recompute when pages run out (vLLM semantics),
- kernel provenance: which attention / MoE implementation was selected (and why a
  fallback fired) is recorded on the engine and surfaced by bench.py — a perf
  number without kernel provenance is undiagnosable,
- P/D roles: ``role=prefill`` stops after prompt processing and exports KV metadata
  (disagg connector picks it up); ``role=decode`` can import KV (disagg/transfer.py).
"""

from __future__ import annotations

import functools
import os
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from llmd_tpu.core.kv_events import KVEvent
from llmd_tpu.core.request import SamplingParams
from llmd_tpu.engine.config import EngineConfig
from llmd_tpu.engine.kv_manager import PageAllocator, Sequence
from llmd_tpu.engine.sampling import (
    greedy_tokens,
    sample_tokens,
    sample_tokens_biased,
)
from llmd_tpu.engine.programs import ProgramRegistry, select_decode_attn_impl
from llmd_tpu.engine.spec import propose_ngram_draft
from llmd_tpu.structured import (
    NEG_BIAS,
    StructuredState,
    compile_grammar,
    parse_logit_bias,
    structured_spec,
)
from llmd_tpu.models.config import ModelConfig
from llmd_tpu.obs.events import FlightRecorder
from llmd_tpu.obs.metrics import Registry, register_engine_metrics
from llmd_tpu.obs.tracing import global_tracer
from llmd_tpu.models.transformer import (
    forward_core,
    init_cache,
    init_params,
    param_logical_axes,
    ragged_paged_attention_xla,
    unembed,
)
from llmd_tpu.parallel.mesh import build_mesh


def _profile_phase(name: str):
    """Wrap a step-loop phase in a ``jax.profiler.TraceAnnotation`` so an
    on-demand capture (/debug/profile, obs/device.py) attributes host+device
    time to the same phase names the step-duration histogram exports. The
    annotation is a no-op TraceMe when no profiler session is active."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)
        return wrapped
    return deco


@dataclass
class EngineOutput:
    request_id: str
    new_token_ids: list[int]
    finished: bool
    finish_reason: Optional[str] = None
    num_cached_prompt_tokens: int = 0
    prompt_len: int = 0


@dataclass
class EngineStats:
    num_waiting: int = 0
    num_running: int = 0
    kv_utilization: float = 0.0
    total_prefill_tokens: int = 0
    total_decode_tokens: int = 0
    # tokens produced by FUSED decode calls only (excludes the unified-step
    # degrade path, whose wall time lands in time_prefill_steps) — the only
    # numerator that matches time_decode_steps as a denominator
    decode_tokens_fused: int = 0
    total_preemptions: int = 0
    total_offload_loads: int = 0  # blocks pulled back from CPU/FS tiers
    eplb_rebalances: int = 0  # wide-EP expert-placement recomputes
    attn_backend: str = ""  # kernel provenance (bench/debug)
    attn_tune_hash: Optional[str] = None  # active block-size tune table (ops/attn_tune)
    moe_backend: str = ""
    moe_dispatch: str = ""  # "sorted" | "einsum" — routing-dispatch provenance
    moe_dropped_tokens: int = 0  # routed copies dropped past capacity (einsum
    # path only; the sorted path is drop-free by construction)
    kv_cache_dtype: str = ""  # "bf16" | "fp8" — pool dtype provenance
    kv_layout: str = ""  # "padded" | "packed-f" — pool lane layout provenance
    sp_attn_backend: Optional[str] = None  # ring layout when sp>1 wired in
    n_ring_prefill_steps: int = 0  # unified steps served by the ring program
    # Per-phase wall-time attribution (bench.py breakdown — every serving-perf
    # number must be decomposable into where the time actually went):
    time_prefill_steps: float = 0.0  # wall inside unified (mixed/prefill) steps
    time_decode_steps: float = 0.0  # wall inside fused decode calls
    time_spec_steps: float = 0.0  # wall inside speculative verify steps
    time_host_pack: float = 0.0  # host-side batch packing (numpy staging)
    time_device: float = 0.0  # jitted call + device sync (incl. dispatch)
    time_device_decode: float = 0.0  # the decode-call share of time_device
    time_postprocess: float = 0.0  # host output handling after device sync
    n_unified_steps: int = 0
    n_decode_calls: int = 0  # fused decode calls PROCESSED (results applied)
    n_decode_dispatches: int = 0  # fused decode calls LAUNCHED; must equal
    # n_decode_calls once the engine drains — a gap means an in-flight record
    # was orphaned (its sampled tokens silently dropped)
    # Speculative decoding (spec_mode="ngram"): prompt-lookup drafts verified
    # through the flat mixed-batch program (engine/spec.py).
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_rejected: int = 0
    n_spec_verify_steps: int = 0
    # Speculation × structured compose (PERF.md Lever 13): the constrained
    # share of drafted/accepted (rows carrying a grammar or logit_bias),
    # plus the crosscheck mismatch count when spec_structured_crosscheck is
    # on (device-returned FSM state vs host StructuredState.sync; must be 0).
    spec_drafted_constrained: int = 0
    spec_accepted_constrained: int = 0
    spec_fsm_crosscheck_mismatches: int = 0
    # Structured outputs (llmd_tpu/structured): grammar-constrained requests
    # admitted, host-side mask builds (time_mask_build is the feature's only
    # per-step host cost — PERF.md compares it against step wall time), and
    # tokens observed outside the grammar (should stay 0; truncated
    # constrained generations count 1 at retirement).
    structured_requests: int = 0
    structured_mask_builds: int = 0
    structured_violations: int = 0
    time_mask_build: float = 0.0
    # Device-resident decode steady state (PERF.md Lever 12): host pack wall
    # that was hidden behind an in-flight device chain (a dispatch or process
    # was pending when the pack ran) lands here instead of time_host_pack, so
    # time_host_pack keeps meaning SERIALIZED host time on the critical path.
    time_pack_overlap: float = 0.0
    # dispatches that reused the in-flight chain's device-resident outputs
    # (tokens/positions/kv-lens/FSM) instead of a full host re-pack
    n_chained_dispatches: int = 0
    # mask-table stagings for the fused constrained path (one per chain
    # start, not one per step — the per-step host mask build this replaces
    # is what time_mask_build used to count)
    structured_chain_stages: int = 0


class LLMEngine:
    """Single-process engine instance (one model replica over one mesh)."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        engine_cfg: EngineConfig,
        params: Optional[dict[str, jax.Array]] = None,
        event_sink: Optional[Callable[[list[KVEvent]], None]] = None,
        seed: int = 0,
        tokenizer: Optional[object] = None,
    ) -> None:
        self.model_cfg = model_cfg
        self.cfg = engine_cfg
        # Tokenizer for the structured-outputs vocab lift (structured/grammar):
        # optional — engines serving only unconstrained requests never need it,
        # and a structured request without one is rejected at add_request.
        self.tokenizer = tokenizer
        self.mesh = build_mesh(engine_cfg.mesh) if engine_cfg.mesh.num_devices > 1 else None
        R = max(1, engine_cfg.dp_ranks)
        self.num_ranks = R
        if R > 1:
            if engine_cfg.max_batch_size % R or engine_cfg.num_pages % R:
                raise ValueError(
                    f"max_batch_size ({engine_cfg.max_batch_size}) and num_pages "
                    f"({engine_cfg.num_pages}) must divide dp_ranks={R}")
            if engine_cfg.cpu_offload_pages > 0 or engine_cfg.offload_fs_path:
                raise ValueError("KV offload tiers are per-rank state; not yet "
                                 "supported with dp_ranks > 1")
            if engine_cfg.batched_tokens // R < 1:
                raise ValueError(
                    f"batched_tokens ({engine_cfg.batched_tokens}) must be at "
                    f"least dp_ranks={R} (each rank needs a token budget)")
        ppr = engine_cfg.num_pages // R
        self.allocs = [
            PageAllocator(
                ppr, engine_cfg.page_size,
                enable_prefix_caching=engine_cfg.enable_prefix_caching,
                event_sink=event_sink, base_id=r * ppr,
            )
            for r in range(R)
        ]
        self.alloc = self.allocs[0]
        self.slots_per_rank = engine_cfg.max_batch_size // R
        # Shared metrics registry: the engine increments step-loop families
        # here and EngineServer renders them from its /metrics handler (in
        # wide-EP every frontend scrapes this same registry).
        self.registry = Registry()
        self.metrics = register_engine_metrics(self.registry)
        self.metrics.cache_config.labels(
            block_size=engine_cfg.page_size,
            num_gpu_blocks=engine_cfg.num_pages).set(1)
        self.tracer = global_tracer()
        # always-on per-request lifecycle timelines; EngineServer exposes
        # this recorder at /debug/requests (obs.events)
        self.flight = FlightRecorder.from_env(tracer=self.tracer)
        # latency attribution: every retired timeline folds into the phase
        # ledger and exports llmd_tpu:request_phase_seconds{phase,tenant,model}
        from llmd_tpu.obs.attribution import attach_phase_exporter

        attach_phase_exporter(self.flight, self.metrics.request_phase)
        # decision plane, engine view (obs/decisions.py): spec-decode
        # economics folded per request at retirement. Chained after the
        # phase exporter (on_finish is a single slot). The knob is cached
        # so the retire path reads one bool when the ledger is off.
        from llmd_tpu.obs.decisions import (
            attach_decision_exporter,
            decisions_enabled,
        )

        self._decisions_on = decisions_enabled()
        if self._decisions_on:
            attach_decision_exporter(self.flight, self.metrics,
                                     plane="engine")
        # utilization attribution plane (obs/costmodel.py): analytic roofline
        # costs stamped per dispatch + token-goodput/recompile ledgers. The
        # knob is read ONCE; off leaves self.util None so every dispatch
        # site pays a single `is not None` check and nothing else.
        from llmd_tpu.obs.costmodel import (
            UtilLedger,
            attach_util_exporter,
            util_ledger_enabled,
        )

        self.util = None
        if util_ledger_enabled():
            try:
                _dev_kind = getattr(jax.devices()[0], "device_kind", "")
            except Exception:
                _dev_kind = ""
            self.util = UtilLedger(
                model_cfg, device_kind=_dev_kind,
                quantize_weights=engine_cfg.quantize_weights,
                kv_cache_dtype=engine_cfg.kv_cache_dtype)
            attach_util_exporter(self.util, self.metrics)
        # device-plane monitor (obs/device.py): attached by the owning
        # EngineServer at start(); the dispatch loop stamps its heartbeat
        self.monitor = None
        self.offload = None
        if engine_cfg.cpu_offload_pages > 0 or engine_cfg.offload_fs_path:
            from llmd_tpu.kv.fs_backend import FSKVBackend
            from llmd_tpu.kv.offload import KVOffloadConnector

            fs = FSKVBackend(engine_cfg.offload_fs_path) if engine_cfg.offload_fs_path else None
            self.offload = KVOffloadConnector(
                engine_cfg.cpu_offload_pages,
                staging_blocks=engine_cfg.offload_staging_blocks,
                fs_backend=fs, event_sink=event_sink,
                pages_per_layer=engine_cfg.num_pages,
                metrics=self.metrics, flight=self.flight,
            )
            self.alloc.evict_hook = lambda h, pid: self.offload.on_evict(self.cache, h, pid)
            store = self.offload.store
            self.metrics.offload_saves.set_function(lambda: store.saves)
            self.metrics.offload_loads.set_function(lambda: store.loads)
            self.metrics.offload_demotions.set_function(lambda: store.demotions)
            self.metrics.offload_cpu_blocks.set_function(lambda: len(store))
        # K5: out-of-tree connector — external engine behind the native tiers
        self.kv_connector = None
        self._connector_pool = None
        if engine_cfg.kv_connector:
            import concurrent.futures

            from llmd_tpu.kv.connector_api import build_kv_connector

            self.kv_connector = build_kv_connector(
                engine_cfg.kv_connector, engine_cfg.kv_connector_params)
            # one drain thread: saves stream out in retirement order without
            # ever blocking the locked engine step loop
            self._connector_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kv-connector")
        # N9: cluster-durable prefix tier — write-back queue + hardened client
        # over the remote store. Off unless LLMD_KV_DURABLE_STORE is set.
        self.durable = None
        self.writeback = None
        from llmd_tpu.kv.writeback import (DurableStoreClient,
                                           DurableStoreConfig, WritebackQueue)

        durable_cfg = DurableStoreConfig.from_env()
        if durable_cfg.enabled:
            self.durable = DurableStoreClient(durable_cfg)
            self.writeback = WritebackQueue(
                self.durable, max_blocks=durable_cfg.queue_blocks)
            if self.offload is not None:
                # eviction/demotion paths tee their already-materialized
                # host bytes into the flush queue (no extra device reads)
                self.offload.writeback = self.writeback
            else:

                def _durable_evict(h, pid):
                    P = self.cfg.num_pages
                    L = self.cache.shape[0] // P
                    rows = np.arange(L) * P + pid
                    self.writeback.offer([h], np.asarray(self.cache[rows])[None])

                self.alloc.evict_hook = _durable_evict
        self.waitq: list[deque[Sequence]] = [deque() for _ in range(R)]
        self.waiting = self.waitq[0]  # rank-0 alias (single-rank compat)
        self.running: list[Optional[Sequence]] = [None] * engine_cfg.max_batch_size
        self.seqs: dict[str, Sequence] = {}
        self.stats = EngineStats()
        # engine-emitted predictor training rows (drained by the server's
        # trace-forwarding loop or read directly by offline training)
        self.latency_trace: deque[dict] = deque(maxlen=4096)
        self._key = jax.random.PRNGKey(seed)
        self._outputs: list[EngineOutput] = []
        self._pending_decode: list[dict] = []  # in-flight pipelined decode calls
        # Device-resident decode steady state (PERF.md Lever 12): rotated
        # host-pack buffer sets — pipeline_depth+1 of them so the buffers a
        # still-in-flight dispatch was packed from are never mutated while
        # jnp.asarray may still alias them (the CPU backend zero-copies).
        self._pack_bufs: list[dict[str, "np.ndarray"]] = []
        # staged dense mask tables, LRU-keyed by the participating grammars'
        # identities + pad shape; entries pin (bias_tab, next_tab) on device
        self._mask_tab_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        # (spec probe arming is per-sequence — Sequence.spec_armed: a negative
        # prompt-lookup probe disarms that row until fresh tokens land for it,
        # removing redundant O(context) numpy scans without letting one
        # non-repetitive stream disarm drafting for the whole batch)
        # one in-flight prefill-step sample read (pipelined like decode: the
        # ~RTT-priced np.asarray of the sampled tokens defers until the NEXT
        # unified step is on the device, hiding the read behind its compute)
        self._pending_sample: Optional[dict] = None

        if params is None:
            params = init_params(model_cfg, jax.random.PRNGKey(seed))
        param_axes = param_logical_axes(model_cfg)
        if engine_cfg.quantize_weights:
            if engine_cfg.quantize_weights != "int8":
                raise ValueError(
                    f"unknown quantize_weights={engine_cfg.quantize_weights!r}"
                    " (supported: 'int8')")
            from llmd_tpu.models.quant import quantize_params

            # before sharding: the returned axes dict matches the new tree,
            # so meshed runs shard _q/_scale leaves like their bf16 ancestors
            params, param_axes = quantize_params(model_cfg, params,
                                                 base_axes=param_axes)
        self.quantization = engine_cfg.quantize_weights
        if self.mesh is not None:
            from llmd_tpu.parallel.mesh import shard_pytree

            params = shard_pytree(params, self.mesh, param_axes)
        self.params = params
        if engine_cfg.kv_cache_dtype not in (None, "fp8"):
            raise ValueError(
                f"unknown kv_cache_dtype={engine_cfg.kv_cache_dtype!r}"
                " (supported: 'fp8')")
        self.kv_dtype = (jnp.float8_e4m3fn if engine_cfg.kv_cache_dtype == "fp8"
                         else model_cfg.jax_dtype)
        from llmd_tpu.ops.packed_kv import pack_factor

        if engine_cfg.kv_layout not in ("auto", "padded", "packed"):
            raise ValueError(f"unknown kv_layout={engine_cfg.kv_layout!r} "
                             "(supported: 'auto', 'padded', 'packed')")
        if engine_cfg.spec_mode not in ("off", "ngram"):
            raise ValueError(f"unknown spec_mode={engine_cfg.spec_mode!r} "
                             "(supported: 'off', 'ngram')")
        if engine_cfg.structured_mode not in ("auto", "off"):
            raise ValueError(
                f"unknown structured_mode={engine_cfg.structured_mode!r} "
                "(supported: 'auto', 'off')")
        # cumulative prefix-cache effectiveness (feeds the hit-ratio gauge)
        self._prefix_cached_total = 0
        self._prefix_prompt_total = 0
        self.kv_pack = (pack_factor(model_cfg)
                        if engine_cfg.kv_layout in ("auto", "packed") else 1)
        if engine_cfg.kv_layout == "packed" and self.kv_pack == 1:
            raise ValueError(
                "kv_layout='packed' requires padded_head_dim == f*head_dim "
                f"and num_kv_heads % f == 0; {model_cfg.name} is ineligible")
        self.cache = init_cache(model_cfg, engine_cfg.num_pages,
                                engine_cfg.page_size, dtype=self.kv_dtype,
                                pack=self.kv_pack)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # combined-head dim (2*Hk) shards over tp: K/V pairs stay together.
            # MLA replicates instead — its pool has ONE row (the shared
            # latent plane, axis size 1), and every head's shard needs the
            # full latent anyway (DeepSeek TP layout: heads shard, latent KV
            # replicates)
            spec = (P(None, None, None, None) if model_cfg.is_mla
                    else P(None, None, "tp", None))
            self.cache = jax.device_put(
                self.cache, NamedSharding(self.mesh, spec))

        self._eplb = None
        if engine_cfg.eplb is not None and model_cfg.is_moe:
            self._init_eplb()

        self.lora_registry = None
        self._lora_params: dict[str, jax.Array] = {}
        if engine_cfg.lora is not None:
            if model_cfg.is_mla:
                # the MLA attention branch applies no adapter deltas — serving
                # would silently return base-model outputs under adapter names
                raise ValueError(
                    "LoRA adapters are not supported on MLA models (the "
                    "absorbed-attention path has no adapter hook); remove "
                    "EngineConfig.lora or use a GQA model")
            from llmd_tpu.models.lora import LoRARegistry, init_lora_params

            self.lora_registry = LoRARegistry(engine_cfg.lora.max_adapters)
            # a displaced idle adapter's cached KV is invalid the moment its
            # slot is reassigned
            self.lora_registry.on_evict = lambda name: self._lora_forget(name)
            self._lora_params = init_lora_params(model_cfg, engine_cfg.lora)
            # name -> content-scoped hash key ("name@<weights-digest>"): KV only
            # matches KV computed under the SAME weights — stale generations can
            # never match (HBM, CPU tier, or FS files surviving a restart), while
            # P/D peers and restarts loading the same checkpoint stay compatible.
            self._lora_keys: dict[str, str] = {}
            if self.mesh is not None:
                from llmd_tpu.models.lora import lora_param_logical_axes
                from llmd_tpu.parallel.mesh import shard_pytree

                self._lora_params = shard_pytree(
                    self._lora_params, self.mesh, lora_param_logical_axes(model_cfg))

        cfg = model_cfg
        mesh = self.mesh
        # shape-keyed attention block-size tune table (bench.py's auto-tuner
        # export, ops/attn_tune): an explicit config path pins the table;
        # otherwise LLMD_ATTN_TUNE_FILE resolves lazily inside
        # pick_block_sizes. The short hash rides provenance (stats/bench JSON)
        # so every measured number traces to the table that shaped its kernels.
        from llmd_tpu.ops import attn_tune

        if engine_cfg.attn_tune_file:
            attn_tune.activate(attn_tune.load_table(engine_cfg.attn_tune_file))
        self.attn_tune_hash = attn_tune.active_hash()
        attn = self._select_attn_impl()
        if self.kv_pack > 1:
            from llmd_tpu.ops.packed_kv import make_packed_attn

            # the paged impls (Pallas or XLA) run against the packed pool via
            # slot-placed queries; the ring program below stays unwrapped (it
            # attends over chunk activations, not the pool)
            attn = make_packed_attn(attn, model_cfg, self.kv_pack)
            self.attn_backend += f"+packed{self.kv_pack}"
        attn_decode = select_decode_attn_impl(self, attn)
        moe_impl = self._select_moe_impl()
        moe_dispatch_impl = self._select_moe_dispatch()
        self.stats.attn_backend = self.attn_backend
        self.stats.attn_tune_hash = self.attn_tune_hash
        self.stats.moe_backend = self.moe_backend
        self.stats.moe_dispatch = self.moe_dispatch
        # kernel-vs-fallback visibility without scraping logs: an info-style
        # gauge keyed by the resolved backend + tune-table hash (value 1)
        self.metrics.attn_backend_info.labels(
            backend=self.attn_backend,
            tune=self.attn_tune_hash or "none").set(1)
        self.stats.kv_cache_dtype = ("fp8" if self.kv_dtype == jnp.float8_e4m3fn
                                     else str(jnp.dtype(self.kv_dtype).name))
        self.stats.kv_layout = (f"packed-{self.kv_pack}" if self.kv_pack > 1
                                else "padded")
        use_lora = self.lora_registry is not None
        lora_scale = engine_cfg.lora.scale if use_lora else 1.0
        NT = self.cfg.batched_tokens
        B = engine_cfg.max_batch_size
        k_steps = max(1, engine_cfg.decode_steps)

        def _bind(x, *axes):
            """GSPMD sharding constraint by mesh axis names (no-op off-mesh)."""
            if mesh is None:
                return x
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*axes)))

        def _make_unified(attn_fn):
            def _unified(params, cache, tokens, positions, seq_slots, page_tables,
                         kv_lens, cu_q_lens, num_seqs, lora_tok,
                         mm_embeds=None, mm_mask=None):
                """Flat mixed batch (prefill chunks + decode tokens); returns each
                sequence's last-row logits [B, vocab]."""
                # flat token dim shards over dp×sp jointly: data-parallel decode
                # rows and sequence-parallel long prefills ride the same constraint
                tokens = _bind(tokens, ("dp", "sp"))
                positions = _bind(positions, ("dp", "sp"))
                seq_slots = _bind(seq_slots, ("dp", "sp"))
                hidden, cache, cnt, drop = forward_core(
                    cfg, params, cache, tokens, positions, seq_slots, page_tables,
                    kv_lens, cu_q_lens=cu_q_lens, num_seqs=num_seqs,
                    attn_impl=attn_fn, moe_matmul_impl=moe_impl,
                    lora_indices=lora_tok if use_lora else None,
                    lora_scale=lora_scale,
                    mm_embeds=mm_embeds, mm_mask=mm_mask,
                    moe_dispatch_impl=moe_dispatch_impl,
                )
                last_rows = jnp.clip(cu_q_lens[1 : B + 1] - 1, 0, NT - 1)  # [B]
                logits = unembed(cfg, params, hidden[last_rows])  # [B, vocab]
                return logits, cache, cnt, drop

            return _unified

        def _make_verify(attn_fn):
            def _verify(params, cache, tokens, positions, seq_slots, page_tables,
                        kv_lens, cu_q_lens, num_seqs, lora_tok):
                """Speculative verify: the same flat mixed-batch packing as
                ``_unified``, extended to return the greedy token at EVERY
                packed position instead of only each sequence's last row —
                prompt-lookup drafts are checked against the continuation of
                every chunk position. The [NT, vocab] logits never leave the
                device; the host reads only [NT] int32 argmax tokens."""
                tokens = _bind(tokens, ("dp", "sp"))
                positions = _bind(positions, ("dp", "sp"))
                seq_slots = _bind(seq_slots, ("dp", "sp"))
                hidden, cache, cnt, drop = forward_core(
                    cfg, params, cache, tokens, positions, seq_slots, page_tables,
                    kv_lens, cu_q_lens=cu_q_lens, num_seqs=num_seqs,
                    attn_impl=attn_fn, moe_matmul_impl=moe_impl,
                    lora_indices=lora_tok if use_lora else None,
                    lora_scale=lora_scale,
                    moe_dispatch_impl=moe_dispatch_impl,
                )
                greedy = greedy_tokens(unembed(cfg, params, hidden))  # [NT]
                return greedy, cache, cnt, drop

            return _verify

        def _make_verify_masked(attn_fn):
            def _verify_masked(params, cache, tokens, positions, seq_slots,
                               page_tables, kv_lens, cu_q_lens, num_seqs,
                               lora_tok, fsm0, gidx, bias_tab, next_tab):
                """``_verify`` with the structured-outputs glue fused in: per
                packed position, gather the row's grammar bias at its CURRENT
                FSM state (advanced along the draft via ``next_tab``), apply
                it before the greedy argmax, and return the would-be state
                after each greedy token — so acceptance is computed against
                grammar-legal tokens only and the host adopts the state at
                the last accepted position instead of resyncing the automaton
                (rejected tails roll back FSM state for free, exactly as
                ``_spec_release_tail`` rolls back KV pages).

                ``fsm0/gidx [B]`` are indexed by PACKED ROW (the verify
                plan's order, same as ``sids``), not by slot: ``fsm0`` is the
                state after the row's full committed history — its first
                packed token is the last committed token, so position 0
                masks with ``fsm0`` directly and position j>0 masks with
                ``fsm0`` advanced through draft[0..j-1]. Slot 0 of both
                tables is the zero no-op grammar: unconstrained rows gather
                a zero bias and the f32 cast is monotonic, so their argmax
                is bitwise the unmasked ``greedy_tokens`` result.
                """
                tokens_b = _bind(tokens, ("dp", "sp"))
                positions_b = _bind(positions, ("dp", "sp"))
                seq_slots_b = _bind(seq_slots, ("dp", "sp"))
                hidden, cache, cnt, drop = forward_core(
                    cfg, params, cache, tokens_b, positions_b, seq_slots_b,
                    page_tables, kv_lens, cu_q_lens=cu_q_lens,
                    num_seqs=num_seqs, attn_impl=attn_fn,
                    moe_matmul_impl=moe_impl,
                    lora_indices=lora_tok if use_lora else None,
                    lora_scale=lora_scale,
                    moe_dispatch_impl=moe_dispatch_impl,
                )
                logits = unembed(cfg, params, hidden).astype(jnp.float32)  # [NT, V]
                valid = positions >= 0  # padding rows must not touch any state
                first = jnp.concatenate(
                    [jnp.ones((1,), bool), seq_slots[1:] != seq_slots[:-1]])

                # FSM states depend only on the INPUT draft tokens, not on the
                # argmax results, so a scalar scan over packed positions
                # suffices: each row's running state advances through its own
                # draft (position j masks with the state after draft[0..j-1]).
                def advance(st, x):
                    tok, row, is_first, ok = x
                    cur = jnp.where(is_first, st[row],
                                    next_tab[gidx[row], st[row], tok])
                    st = st.at[row].set(jnp.where(ok, cur, st[row]))
                    return st, jnp.where(ok, cur, 0)

                _, cur_states = jax.lax.scan(
                    advance, fsm0, (tokens, seq_slots, first, valid))
                g_rows = gidx[seq_slots]  # [NT]
                greedy = jnp.argmax(logits + bias_tab[g_rows, cur_states],
                                    axis=-1).astype(jnp.int32)
                fsm_next = next_tab[g_rows, cur_states, greedy]  # [NT]
                return greedy, fsm_next, cache, cnt, drop

            return _verify_masked

        def _decode_multi(params, cache, tokens, positions, page_tables, kv_lens,
                          temp, top_k, top_p, key, steps_left, lora_idx):
            """k decode iterations fused on-device (lax.scan): feed sampled token back
            each step; one host round-trip per k tokens instead of per token.

            ``steps_left [B]`` caps each row device-side (0 = idle slot): rows
            freeze once their per-row budget (max_tokens / max_model_len
            remaining) is spent, so a fused call may safely overrun a sequence's
            end — required by the pipelined dispatch path, where the host reads
            results one call behind.
            """
            tokens = _bind(tokens, "dp")
            positions = _bind(positions, "dp")
            page_tables = _bind(page_tables, "dp", None)
            kv_lens = _bind(kv_lens, "dp")
            seq_slots = jnp.arange(B, dtype=jnp.int32)
            cu = jnp.arange(B + 1, dtype=jnp.int32)
            ns = jnp.array([B], jnp.int32)

            def body(carry, i):
                cache, toks, pos, lens, key = carry
                hidden, cache, cnt, drop = forward_core(
                    cfg, params, cache, toks, pos, seq_slots, page_tables, lens,
                    cu_q_lens=cu, num_seqs=ns, attn_impl=attn_decode,
                    moe_matmul_impl=moe_impl,
                    lora_indices=lora_idx if use_lora else None,
                    lora_scale=lora_scale,
                    moe_dispatch_impl=moe_dispatch_impl,
                )
                logits = unembed(cfg, params, hidden)  # [B, vocab]
                key, sub = jax.random.split(key)
                nxt = sample_tokens(logits, sub, temp, top_k, top_p)
                act = i < steps_left
                nxt = jnp.where(act, nxt, 0)
                pos = jnp.where(act, pos + 1, pos)
                lens = jnp.where(act, lens + 1, lens)
                return (cache, nxt, pos, lens, key), (nxt, cnt, drop)

            (cache, last_toks, pos_out, lens_out, _), (toks_out, cnts, drops) = jax.lax.scan(
                body, (cache, tokens, positions, kv_lens, key),
                jnp.arange(k_steps, dtype=jnp.int32),
            )
            # last_toks/pos_out/lens_out: device-resident chain point for the
            # next pipelined call — a chained dispatch reuses them instead of
            # re-packing positions and kv lens on the host
            return (toks_out, last_toks, pos_out, lens_out, cache, cnts.sum(0),
                    drops.sum())

        def _decode_multi_masked(params, cache, tokens, positions, page_tables,
                                 kv_lens, temp, top_k, top_p, key, steps_left,
                                 lora_idx, fsm_state, gidx, bias_tab, next_tab):
            """``_decode_multi`` with the structured-outputs glue fused in:
            per step, each row gathers its grammar's bias row at its current
            FSM state from ``bias_tab [G, S, V]``, samples through the same
            biased sampler the host path uses (f32 cast first — bitwise parity
            with ``_sample_dispatch``), and advances its automaton through
            ``next_tab [G, S, V] i32``. Slot 0 of both tables is the zero
            no-op grammar, so unconstrained rows ride along unbiased.

            The FSM state is part of the scan carry and of the return value:
            a chained dispatch passes the previous call's ``fsm_out`` back in,
            keeping the automaton device-resident for the whole chain. Frozen
            rows (``steps_left`` spent) hold their state, mirroring the
            host-side freeze in ``StructuredState.sync``.
            """
            tokens = _bind(tokens, "dp")
            positions = _bind(positions, "dp")
            page_tables = _bind(page_tables, "dp", None)
            kv_lens = _bind(kv_lens, "dp")
            seq_slots = jnp.arange(B, dtype=jnp.int32)
            cu = jnp.arange(B + 1, dtype=jnp.int32)
            ns = jnp.array([B], jnp.int32)

            def body(carry, i):
                cache, toks, pos, lens, key, st = carry
                hidden, cache, cnt, drop = forward_core(
                    cfg, params, cache, toks, pos, seq_slots, page_tables, lens,
                    cu_q_lens=cu, num_seqs=ns, attn_impl=attn_decode,
                    moe_matmul_impl=moe_impl,
                    lora_indices=lora_idx if use_lora else None,
                    lora_scale=lora_scale,
                    moe_dispatch_impl=moe_dispatch_impl,
                )
                logits = unembed(cfg, params, hidden).astype(jnp.float32)
                row_bias = bias_tab[gidx, st]  # [B, vocab]
                key, sub = jax.random.split(key)
                nxt = sample_tokens_biased(logits, row_bias, sub, temp, top_k,
                                           top_p)
                new_st = next_tab[gidx, st, nxt]  # [B]
                act = i < steps_left
                st = jnp.where(act, new_st, st)
                nxt = jnp.where(act, nxt, 0)
                pos = jnp.where(act, pos + 1, pos)
                lens = jnp.where(act, lens + 1, lens)
                return (cache, nxt, pos, lens, key, st), (nxt, cnt, drop)

            (cache, last_toks, pos_out, lens_out, _, fsm_out), (toks_out, cnts,
                                                                drops) = (
                jax.lax.scan(
                    body, (cache, tokens, positions, kv_lens, key, fsm_state),
                    jnp.arange(k_steps, dtype=jnp.int32),
                ))
            return (toks_out, last_toks, pos_out, lens_out, fsm_out, cache,
                    cnts.sum(0), drops.sum())

        def _embed(params, cache, tokens, positions, page_tables, kv_lens,
                   cu_q_lens, lora_idx):
            """Prefill chunk returning the sum of valid positions' final hidden
            states — the pooling accumulator for /v1/embeddings."""
            tokens = _bind(tokens, ("dp", "sp"))
            positions = _bind(positions, ("dp", "sp"))
            seq_slots = jnp.zeros_like(tokens)
            hidden, cache, _cnt, _drop = forward_core(
                cfg, params, cache, tokens, positions, seq_slots, page_tables,
                kv_lens, cu_q_lens=cu_q_lens, num_seqs=jnp.array([1], jnp.int32),
                attn_impl=attn, moe_matmul_impl=moe_impl,
                lora_indices=lora_idx if use_lora else None, lora_scale=lora_scale,
                moe_dispatch_impl=moe_dispatch_impl,
            )
            valid = (positions >= 0).astype(jnp.float32)[:, None]
            return jnp.sum(hidden.astype(jnp.float32) * valid, axis=0), cache

        donate = dict(donate_argnums=(1,))  # cache is donated — updated in place in HBM
        # Step-program registry (engine/programs.py): every compiled program
        # is a declarative entry. Routable entries carry an eligibility
        # predicate + run hook (registration order = priority; step() is just
        # `route(self).run(self)`); variants without one (masked/ring, embed)
        # are dispatched BY a routable program. jax.jit is lazy throughout —
        # registering costs nothing until a program's first dispatch, so
        # spec_mode="off" engines never compile the verify programs and
        # unconstrained serving never compiles the masked ones. The engine
        # keeps its `self._*_fn` aliases: tests and the hot-path linter key
        # on the `self._*_fn(...)` call spelling.
        self.programs = ProgramRegistry(
            on_dispatch=lambda name:
                self.metrics.program_dispatches.labels(program=name).inc())
        _register = self.programs.register
        self._unified_fn = _register(
            "unified", jax.jit(_make_unified(attn), **donate), attn="mixed",
            eligible=LLMEngine._unified_eligible,
            run=LLMEngine._run_unified_program)
        self._verify_fn = _register(
            "verify", jax.jit(_make_verify(attn), **donate), attn="mixed",
            eligible=lambda eng: eng.cfg.spec_mode == "ngram",
            run=LLMEngine._run_verify_program)
        self._verify_masked_fn = _register(
            "verify_masked", jax.jit(_make_verify_masked(attn), **donate),
            attn="mixed")
        self._decode_multi_fn = _register(
            "decode", jax.jit(_decode_multi, **donate), attn="decode",
            eligible=lambda eng: True,  # terminal entry: always routable
            run=LLMEngine._run_decode_program)
        self._decode_multi_masked_fn = _register(
            "decode_masked", jax.jit(_decode_multi_masked, **donate),
            attn="decode")
        self._embed_fn = _register("embed", jax.jit(_embed, **donate),
                                   attn="mixed")

        # "attn" step-phase probe: a jitted attention-ONLY call at the live
        # decode shape (real pool, layer-0 page tables), run every
        # _attn_probe_every fused dispatches and observed into
        # step_duration{phase="attn"} scaled by layers x k — an estimate of
        # the fused call's attention share, directly comparable against the
        # decode_dispatch samples (PERF.md roofline reconciliation). Sampled
        # because a per-step device sync would serialize the pipelined
        # dispatch path it is trying to measure.
        dhp_kv = self.cache.shape[-1]
        attn_probe_scale = ((cfg.mla_qk_nope_dim + cfg.mla_rope_dim) ** -0.5
                            if cfg.is_mla else cfg.head_dim ** -0.5)

        def _attn_probe(cache, page_tables, kv_lens):
            q = jnp.zeros((B, cfg.num_heads, dhp_kv), cfg.jax_dtype)
            return attn_decode(
                q, cache, page_tables, kv_lens - 1,
                jnp.arange(B, dtype=jnp.int32), kv_lens,
                scale=attn_probe_scale,
                cu_q_lens=jnp.arange(B + 1, dtype=jnp.int32),
                num_seqs=jnp.array([B], jnp.int32))

        self._attn_probe_fn = jax.jit(_attn_probe)
        self._attn_probe_every = 64
        self._attn_probe_warm = False

        # MoE step-phase probe (sorted path only): jitted dispatch / experts /
        # combine stage calls at the fused-decode token shape, sampled on the
        # same cadence as the attn probe and observed into
        # step_duration{phase="moe_dispatch"|"moe_experts"|"moe_combine"}
        # scaled by layers x k. This is the DBO measurement surface: the
        # dispatch sample bounds the all-to-all/permute wall a half-batch can
        # hide behind the other half's expert GEMMs (experts sample), so the
        # overlap claim is read off the phase ledger instead of asserted.
        self._moe_probe_fns = None
        self._moe_probe_warm = False
        if cfg.is_moe and self.moe_dispatch == "sorted":
            from llmd_tpu.ops import moe_dispatch as moe_dispatch_ops

            probe_S = (self._eplb_slots if self._eplb is not None
                       else cfg.moe_num_experts)
            probe_pallas = self.moe_backend == "pallas_grouped_gemm"
            probe_bc = moe_dispatch_ops.pick_block_size(
                B * cfg.moe_top_k, probe_S, probe_pallas)

            def _moe_dispatch_probe(x, idx, topw, valid):
                return moe_dispatch_ops.dispatch_stage(
                    x, idx, topw, valid, probe_S, probe_bc)

            def _moe_experts_probe(xs, block_slot, block_rows, wi, wo,
                                   wi_scale, wo_scale):
                return moe_dispatch_ops.experts_stage(
                    xs, block_slot, block_rows, wi, wo, wi_scale, wo_scale,
                    use_pallas=probe_pallas)

            def _moe_combine_probe(ye, row, tok, wf):
                return moe_dispatch_ops.combine_stage(ye, row, tok, wf, B)

            self._moe_probe_fns = (jax.jit(_moe_dispatch_probe),
                                   jax.jit(_moe_experts_probe),
                                   jax.jit(_moe_combine_probe))
        # SP long-context prefill: a second unified program whose attention is
        # the zig-zag ring over the sp axis (ops/ring_attention.py), engaged
        # host-side for self-contained single-sequence prefill steps only —
        # the regime where the S² attention term lives and context parallelism
        # pays (SURVEY §5 long-context; compiled lazily on first eligible step)
        self._unified_ring_fn = None
        self.sp_attn_backend: Optional[str] = None
        if (mesh is not None and engine_cfg.mesh.sp > 1
                and engine_cfg.sp_ring_attention and NT % engine_cfg.mesh.sp == 0):
            # MLA composes: absorbed attention is MQA over the latent (Hk=1,
            # G=H in the ring's grouped layout) and the latent rides the ICI
            # ring at rank+rope width — 4-8x fewer ring bytes than GQA KV.
            # Parity pinned by tests/test_mla.py::test_ring_prefill_parity_under_sp.
            from llmd_tpu.ops.ring_attention import make_ring_attn_impl

            # ONE layout decision, passed down — sp_flash_prefill would
            # otherwise re-derive it independently and a future change to its
            # degrade condition would make this provenance label lie
            layout = "zigzag" if NT % (2 * engine_cfg.mesh.sp) == 0 else "contiguous"
            ring = make_ring_attn_impl(mesh, axis_name="sp",
                                       zigzag=(layout == "zigzag"))
            self._unified_ring_fn = _register(
                "unified_ring", jax.jit(_make_unified(ring), **donate),
                attn="mixed")
            self.sp_attn_backend = f"ring_{layout}(sp={engine_cfg.mesh.sp})"
            self.stats.sp_attn_backend = self.sp_attn_backend

    # ------------------------------------------------------- kernel selection
    def _select_attn_impl(self):
        """Pick the attention kernel: Pallas ragged-paged-attention on TPU (after a
        smoke compile), XLA gather+mask reference elsewhere or on kernel failure.
        Records provenance in ``attn_backend`` / ``attn_fallback_reason``."""
        self.attn_fallback_reason: Optional[str] = None
        mode = self.cfg.attn_impl
        if self.model_cfg.is_mla:
            # Absorbed MLA runs as MQA with head_dim = latent rank + rope dim
            # (typically 288–640 lanes) — past the GQA Pallas kernel's
            # supported head sizes; the XLA impl handles the mixed-batch
            # programs (unified/verify/embed) at any width. The fused-decode
            # program upgrades to the latent-width Pallas kernel in
            # programs.select_decode_attn_impl — decode is where the KV
            # stream lives.
            # xla_mla_absorbed is the DESIGNED mixed-batch backend for MLA,
            # not a degradation — provenance lives in attn_backend alone so
            # fallback alerts stay quiet on healthy MLA engines
            self.attn_backend = "xla_mla_absorbed"
            return ragged_paged_attention_xla
        if mode == "reference":
            self.attn_backend = "xla_reference"
            return ragged_paged_attention_xla
        want_pallas = mode == "pallas" or (
            mode == "auto" and jax.default_backend() == "tpu"
        )
        if not want_pallas:
            self.attn_backend = "xla_reference"
            self.attn_fallback_reason = f"backend={jax.default_backend()} (non-TPU)"
            return ragged_paged_attention_xla
        from llmd_tpu.ops.paged_attention import paged_attention_tpu

        try:  # smoke-compile on tiny shapes so a Mosaic failure can't strand serving
            from llmd_tpu.models.transformer import padded_head_dim

            c = self.model_cfg
            dhp = padded_head_dim(c.head_dim)
            ps = self.cfg.page_size
            q = jnp.zeros((1, c.num_heads, dhp), c.jax_dtype)
            # smoke at the SERVING cache dtype AND layout — an fp8 strided-load
            # or packed-shape failure must surface here (and fall back) rather
            # than strand serving
            cache = jnp.zeros(
                (2, ps, 2 * (c.num_kv_heads // self.kv_pack), dhp),
                self.kv_dtype)
            paged_attention_tpu(
                q, cache, jnp.zeros((1, 2), jnp.int32), jnp.zeros((1,), jnp.int32),
                jnp.zeros((1,), jnp.int32), jnp.ones((1,), jnp.int32),
                scale=c.head_dim ** -0.5,
                cu_q_lens=jnp.array([0, 1], jnp.int32),
                num_seqs=jnp.array([1], jnp.int32),
            ).block_until_ready()
            self.attn_backend = "pallas_ragged_paged_attention"
            return paged_attention_tpu
        except Exception as e:  # noqa: BLE001 — any Mosaic/XLA compile error
            if mode == "pallas":
                raise
            self.attn_backend = "xla_reference"
            self.attn_fallback_reason = f"pallas smoke-compile failed: {type(e).__name__}: {e}"
            return ragged_paged_attention_xla

    # (the fused-decode attention-impl selector moved to
    # llmd_tpu.engine.programs.select_decode_attn_impl — it is step-program
    # metadata, resolved once at startup before the programs are registered)

    def _select_moe_impl(self):
        """Pick the MoE expert-GEMM path: Pallas grouped GEMM on TPU (after a smoke
        compile), XLA einsum elsewhere or on kernel failure."""
        self.moe_fallback_reason: Optional[str] = None
        if not self.model_cfg.is_moe:
            self.moe_backend = "n/a (dense model)"
            return None
        if self.cfg.quantize_weights == "int8":
            # int8 expert banks run the scaled-einsum path (moe_block);
            # the Pallas grouped GEMM is bf16-only — an EXPLICIT pallas
            # request conflicts and must fail loudly, like every other
            # explicit-mode contract in backend selection
            if self.cfg.moe_matmul == "pallas":
                raise ValueError(
                    "moe_matmul='pallas' (grouped GEMM, bf16-only) is "
                    "incompatible with quantize_weights='int8'")
            self.moe_backend = "xla_einsum (int8 weights)"
            self.moe_fallback_reason = "int8 weights (grouped GEMM is bf16-only)"
            return None
        mode = self.cfg.moe_matmul
        if mode == "einsum":
            self.moe_backend = "xla_einsum"
            return None
        want = mode == "pallas" or (mode == "auto" and jax.default_backend() == "tpu")
        if not want:
            self.moe_backend = "xla_einsum"
            self.moe_fallback_reason = f"backend={jax.default_backend()} (non-TPU)"
            return None
        from llmd_tpu.ops.grouped_gemm import grouped_gemm, make_moe_matmul

        try:
            grouped_gemm(
                jnp.zeros((2, 8, 16), self.model_cfg.jax_dtype),
                jnp.zeros((2, 16, 128), self.model_cfg.jax_dtype),
                jnp.array([1, 0], jnp.int32),
            ).block_until_ready()
            self.moe_backend = "pallas_grouped_gemm"
            return make_moe_matmul()
        except Exception as e:  # noqa: BLE001
            if mode == "pallas":
                raise
            self.moe_backend = "xla_einsum"
            self.moe_fallback_reason = f"pallas smoke-compile failed: {type(e).__name__}: {e}"
            return None

    def _select_moe_dispatch(self):
        """Pick the MoE routing-dispatch path (orthogonal to the expert-GEMM
        backend above): token-sorted drop-free (ops/moe_dispatch) vs the
        legacy capacity-einsum reference. ``EngineConfig.moe_dispatch`` =
        auto|sorted|einsum; auto honours LLMD_MOE_DISPATCH and otherwise
        resolves to sorted everywhere — einsum stays as the parity
        reference and kill switch. Returns the dispatch_impl closure (or
        None for einsum); provenance in ``moe_dispatch`` /
        ``moe_dispatch_fallback_reason``."""
        self.moe_dispatch_fallback_reason: Optional[str] = None
        if not self.model_cfg.is_moe:
            self.moe_dispatch = "n/a (dense model)"
            return None
        mode = self.cfg.moe_dispatch
        if mode == "auto":
            mode = os.environ.get("LLMD_MOE_DISPATCH", "") or "sorted"
        if mode not in ("sorted", "einsum"):
            raise ValueError(
                f"moe_dispatch must be auto|sorted|einsum, got {mode!r}")
        if mode == "einsum":
            self.moe_dispatch = "einsum"
            return None
        # slot dim must divide the ep axis for the bucketed all_to_all;
        # EPLB already rounds its slot count up (_init_eplb), so only the
        # bare expert count can mismatch
        ep = max(1, self.cfg.mesh.ep) if self.mesh is not None else 1
        S = self._eplb_slots if self._eplb is not None \
            else self.model_cfg.moe_num_experts
        if S % ep:
            self.moe_dispatch = "einsum"
            self.moe_dispatch_fallback_reason = (
                f"expert slots ({S}) do not divide the ep axis ({ep})")
            return None
        from llmd_tpu.ops.moe_dispatch import make_sorted_dispatch

        # expert GEMMs ride the ragged Pallas kernel exactly when the
        # einsum path would have used the grouped Pallas kernel (bf16 on
        # TPU); CPU and int8 banks use the gathered-einsum block backend
        use_pallas = self.moe_backend == "pallas_grouped_gemm"
        self.moe_dispatch = "sorted"
        return make_sorted_dispatch(self.mesh, use_pallas=use_pallas)

    # ----------------------------------------------------------------- EPLB
    # Wide-EP expert load balancing (reference --enable-eplb, wide-ep
    # decode.yaml:114-118). Physical slot weights + replica tables live beside the
    # logical params and are re-gathered every step_interval engine steps; all
    # shapes are fixed (R padded to its max) so the step programs never recompile.
    def _init_eplb(self) -> None:
        from llmd_tpu.parallel.eplb import ExpertLoadTracker

        e = self.cfg.eplb
        E, L = self.model_cfg.moe_num_experts, self.model_cfg.num_layers
        ep = max(1, self.cfg.mesh.ep)
        S = E + e.num_redundant_experts
        S += (-S) % ep  # slot dim shards evenly over the ep axis
        self._eplb = e
        self._eplb_slots = S
        self._eplb_rmax = S - E + 1  # one expert could own every redundant slot
        self._eplb_tracker = ExpertLoadTracker(L, E, e.window_size)
        self._eplb_steps = 0
        self._eplb_active = False  # set when a forward actually routed tokens

        mesh = self.mesh

        def _gather(wi, wo, s2e, wi_s=None, wo_s=None):
            l = jnp.arange(wi.shape[0])[:, None]
            wi_p, wo_p = wi[l, s2e], wo[l, s2e]
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                wi_p = jax.lax.with_sharding_constraint(
                    wi_p, NamedSharding(mesh, P(None, "ep", None, "tp")))
                wo_p = jax.lax.with_sharding_constraint(
                    wo_p, NamedSharding(mesh, P(None, "ep", "tp", None)))
            if wi_s is None:
                return wi_p, wo_p
            # int8 expert banks: the per-expert scales regather by the SAME
            # slot map — slot weights and their scales move together
            wi_sp, wo_sp = wi_s[l, s2e], wo_s[l, s2e]
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                # scales shard with their weights' surviving axes: wi keeps
                # its tp-sharded output channels, wo's outputs are unsharded
                wi_sp = jax.lax.with_sharding_constraint(
                    wi_sp, NamedSharding(mesh, P(None, "ep", "tp")))
                wo_sp = jax.lax.with_sharding_constraint(
                    wo_sp, NamedSharding(mesh, P(None, "ep", None)))
            return wi_p, wo_p, wi_sp, wo_sp

        self._eplb_gather = jax.jit(_gather)
        self._eplb_rebalance()

    def _eplb_rebalance(self) -> None:
        from llmd_tpu.parallel.eplb import balance_ratio, rebalance

        ep = max(1, self.cfg.mesh.ep)
        loads = self._eplb_tracker.loads()
        # imbalance under the OUTGOING placement (what serving just ran with):
        # max/mean routed tokens per EP rank, averaged over layers — the
        # "before" half of the rebalance-effectiveness pair on /metrics
        if getattr(self, "_eplb_s2e", None) is not None:
            self.metrics.moe_ep_imbalance.labels(when="before").set(
                float(np.mean([
                    balance_ratio(loads[l], self._eplb_s2e[l],
                                  self._eplb_counts[l], ep)
                    for l in range(loads.shape[0])])))
        s2e, slots, counts = rebalance(loads, self._eplb_slots, ep)
        self.metrics.moe_ep_imbalance.labels(when="after").set(
            float(np.mean([
                balance_ratio(loads[l], s2e[l], counts[l], ep)
                for l in range(loads.shape[0])])))
        self._eplb_counts = counts
        L, E, R = slots.shape
        if R < self._eplb_rmax:  # pad replica dim to its fixed max (no recompiles)
            pad = np.repeat(slots[:, :, :1], self._eplb_rmax - R, axis=2)
            slots = np.concatenate([slots, pad], axis=2)
        if "moe_wi_q" in self.params:  # int8 expert banks
            wi_p, wo_p, wi_sp, wo_sp = self._eplb_gather(
                self.params["moe_wi_q"], self.params["moe_wo_q"],
                jnp.asarray(s2e), self.params["moe_wi_scale"],
                self.params["moe_wo_scale"])
            extra = {"moe_wi_q": wi_p, "moe_wo_q": wo_p,
                     "moe_wi_scale": wi_sp, "moe_wo_scale": wo_sp}
        else:
            wi_p, wo_p = self._eplb_gather(
                self.params["moe_wi"], self.params["moe_wo"], jnp.asarray(s2e))
            extra = {"moe_wi": wi_p, "moe_wo": wo_p}
        self._eplb_params = {
            **extra,
            "eplb_replica_slots": jnp.asarray(slots),
            "eplb_replica_counts": jnp.asarray(counts),
        }
        self._eplb_s2e = s2e
        self.stats.eplb_rebalances += 1

    def _run_params(self) -> dict[str, jax.Array]:
        """Params seen by the step programs: base weights, plus physical expert
        weights under EPLB, plus the LoRA adapter bank when enabled."""
        if self._eplb is None and not self._lora_params:
            return self.params
        merged = dict(self.params)
        if self._eplb is not None:
            merged.update(self._eplb_params)
        merged.update(self._lora_params)
        return merged

    # ----------------------------------------------------------------- LoRA
    # Dynamic adapter serving (model-servers.md:55-75; adapter-rollout.md:11-31).
    # Loading writes one slot of the fixed-shape device bank — step programs
    # never recompile as adapters come and go.
    def _lora_slot(self, seq: "Sequence") -> int:
        if self.lora_registry is None:
            return 0
        return self.lora_registry.slot_of(seq.lora_id)

    def _lora_hash_key(self, name: Optional[str]) -> Optional[str]:
        """The lora term used in block hashing: generation-scoped when LoRA
        serving is on, the plain name otherwise (test fixtures etc.)."""
        if name is None or self.lora_registry is None:
            return name
        return self._lora_keys.get(name, name)

    def _lora_forget(self, name: str) -> None:
        """Retire a name's KV: reclaim HBM pages now (from every rank's
        partition); the dropped generation key guarantees tiered copies (CPU/FS)
        never match again."""
        self._lora_keys.pop(name, None)
        for alloc in self.allocs:
            alloc.purge_lora(name)

    def load_lora_adapter(self, name: str, weights: Optional[dict] = None,
                          seed: Optional[int] = None) -> int:
        """Install an adapter into a free slot. ``weights`` maps
        lora_{A,B}_{target} -> [L, ...] arrays; None generates a random test
        double (the filesystem-resolver path loads real weights and calls this)."""
        if self.lora_registry is None:
            raise RuntimeError("engine built without EngineConfig.lora")
        from llmd_tpu.models.lora import make_adapter_weights

        if self.lora_registry.has(name):
            if self.lora_registry.running.get(name) or self.lora_registry.waiting.get(name):
                # same guard as unload: swapping weights under live sequences
                # would mix two checkpoints in one generation
                raise RuntimeError(f"adapter {name!r} has in-flight requests")
            self._lora_forget(name)  # old generation's KV must never match again
        slot = self.lora_registry.assign(name)
        import hashlib

        if weights is None:
            # deterministic per name (not per process): P/D peers generating the
            # same test double agree on weights, hence on the content digest
            name_seed = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "big")
            weights = make_adapter_weights(
                self.model_cfg, self.cfg.lora,
                jax.random.PRNGKey(seed if seed is not None else name_seed))

        digest = hashlib.sha256()
        for k in sorted(weights):
            digest.update(k.encode())
            digest.update(np.ascontiguousarray(np.asarray(weights[k])).tobytes())
        self._lora_keys[name] = f"{name}@{digest.hexdigest()[:16]}"
        for key in self._lora_params:  # zero first: partial weight sets must not
            if key not in weights:     # inherit a displaced adapter's leftovers
                self._lora_params[key] = self._lora_params[key].at[:, slot].set(0)
        for key, w in weights.items():
            if key not in self._lora_params:
                raise KeyError(f"unknown LoRA param {key!r}")
            self._lora_params[key] = self._lora_params[key].at[:, slot].set(
                jnp.asarray(w, self._lora_params[key].dtype))
        return slot

    def unload_lora_adapter(self, name: str) -> bool:
        if self.lora_registry is None:
            return False
        if self.lora_registry.running.get(name) or self.lora_registry.waiting.get(name):
            # in-flight guard: freeing the slot mid-generation would silently
            # switch live sequences to base weights (and let the slot be reused)
            raise RuntimeError(f"adapter {name!r} has in-flight requests")
        slot = self.lora_registry.remove(name)
        if slot is None:
            return False
        for key in self._lora_params:  # zero the slot: it is the null adapter again
            self._lora_params[key] = self._lora_params[key].at[:, slot].set(0)
        # reclaim HBM now; the dropped generation key keeps every tier safe
        self._lora_forget(name)
        return True

    def _eplb_record(self, cnt: jax.Array) -> None:
        self._eplb_tracker.record(np.asarray(cnt))
        self._eplb_active = True

    def _moe_record_dropped(self, drop) -> None:
        """Surface the silent-capacity-drop bug: every routed copy the legacy
        einsum path dropped past capacity C counts here (the sorted path
        returns a structural 0 — moe_check asserts the scrape stays 0).
        Called where the step's outputs are already host-synced (or one call
        behind on the pipelined decode path), so the scalar read adds no
        device sync of its own."""
        if not self.model_cfg.is_moe:
            return
        n = int(np.asarray(drop))
        self.stats.moe_dropped_tokens += n
        self.metrics.moe_dropped_tokens.labels(
            path=self.stats.moe_dispatch or "einsum").inc(n)

    def _eplb_tick(self) -> None:
        # Count only steps that routed tokens — idle wave steps (DP lockstep with
        # no local work) must not burn rebalances, each of which re-gathers the
        # full expert weights on device.
        if not self._eplb_active:
            return
        self._eplb_active = False
        self._eplb_steps += 1
        if self._eplb_steps % self._eplb.step_interval == 0:
            self._eplb_rebalance()

    # ------------------------------------------------------------------ API
    def add_request(
        self,
        request_id: str,
        token_ids: list[int],
        sampling: Optional[SamplingParams] = None,
        lora_id: Optional[str] = None,
        rank: int = 0,
        mm_items: Optional[list[tuple[bytes, np.ndarray]]] = None,
        trace_ctx: Optional[object] = None,
    ) -> None:
        sampling = sampling or SamplingParams()
        if not token_ids:
            raise ValueError("empty prompt")
        if not (0 <= rank < self.num_ranks):
            raise ValueError(f"rank {rank} out of range (dp_ranks={self.num_ranks})")
        if len(token_ids) >= self.cfg.max_model_len:
            token_ids = token_ids[: self.cfg.max_model_len - 1]
        ps = self.cfg.page_size
        if (len(token_ids) + 1 + ps - 1) // ps > self.allocs[rank].num_pages:
            raise ValueError(
                f"prompt needs more KV pages than the rank's pool "
                f"({len(token_ids)} tokens, {self.allocs[rank].num_pages} pages × {ps})"
            )
        if lora_id and self.lora_registry is not None and not self.lora_registry.has(lora_id):
            # vLLM returns 404 for unknown adapters; silently serving base
            # weights would also poison the prefix cache under this name
            raise ValueError(f"unknown LoRA adapter {lora_id!r}")
        mm_items = mm_items or []
        if mm_items:
            k = self.model_cfg.mm_tokens
            if k <= 0:
                raise ValueError("model has no vision tower (mm_tokens=0)")
            n_ph = sum(1 for t in token_ids if t == self.model_cfg.mm_placeholder_id)
            if n_ph != k * len(mm_items):
                raise ValueError(
                    f"{len(mm_items)} media items need {k * len(mm_items)} "
                    f"placeholder tokens, prompt has {n_ph}")
            for h, emb in mm_items:
                if emb.shape != (k, self.model_cfg.hidden_size):
                    raise ValueError(f"mm embedding shape {emb.shape} != "
                                     f"({k}, {self.model_cfg.hidden_size})")
        # Structured outputs: compile (or cache-fetch) the token grammar BEFORE
        # any engine state mutates, so a malformed spec raises ValueError (the
        # server's 400 path) without leaking a queued sequence.
        logit_bias = parse_logit_bias(sampling.logit_bias)
        structured: Optional[StructuredState] = None
        compile_meta: Optional[tuple[str, bool, float]] = None
        spec = structured_spec(sampling)
        if spec is not None:
            if self.cfg.structured_mode == "off":
                raise ValueError(
                    "structured outputs are disabled (structured_mode='off')")
            if self.tokenizer is None:
                raise ValueError(
                    "structured request needs a tokenizer-equipped engine "
                    "(LLMEngine(..., tokenizer=...))")
            kind, payload = spec
            tc0 = time.perf_counter()
            grammar, cache_hit = compile_grammar(
                kind, payload, self.tokenizer, self.model_cfg.vocab_size)
            compile_s = time.perf_counter() - tc0
            structured = StructuredState(grammar, kind)
            compile_meta = (kind, cache_hit, compile_s)
            self.stats.structured_requests += 1
            m = self.metrics
            m.structured_requests.labels(kind=kind).inc()
            (m.structured_cache_hits if cache_hit
             else m.structured_cache_misses).inc()
            m.structured_compile_seconds.observe(compile_s)
        seq = Sequence(
            request_id=request_id, token_ids=list(token_ids), prompt_len=len(token_ids),
            max_tokens=sampling.max_tokens, sampling=sampling, lora_id=lora_id,
            lora_key=self._lora_hash_key(lora_id), arrival_time=time.monotonic(),
            rank=rank, mm_items=mm_items, trace_ctx=trace_ctx,
        )
        seq.structured = structured
        seq.logit_bias = logit_bias
        # pod state as a router would have observed it at arrival — joined with
        # the observed latencies at retirement into one predictor training row
        inflight = sum(
            len(s.token_ids) for s in self.running if s is not None
        ) + sum(s.prompt_len for q in self.waitq for s in q)
        seq.admit_features = {
            "kv_usage": sum(a.num_active for a in self.allocs) / max(1, self.cfg.num_pages),
            "input_len": float(len(token_ids)),
            "queue_depth": float(sum(len(q) for q in self.waitq)),
            "running_requests": float(sum(1 for s in self.running if s is not None)),
            "inflight_tokens": float(inflight),
            "prefix_match_pct": 0.0,  # known at admission; patched there
        }
        self.seqs[request_id] = seq
        self.waitq[rank].append(seq)
        self.flight.start(request_id, model=self.model_cfg.name,
                          trace_id=getattr(trace_ctx, "trace_id", "") or "")
        self.flight.record(request_id, "arrival", prompt_len=len(token_ids),
                           rank=rank, lora=lora_id)
        if compile_meta is not None:
            kind, cache_hit, compile_s = compile_meta
            self.flight.record(request_id, "structured_compile", kind=kind,
                               cache_hit=cache_hit,
                               compile_ms=round(compile_s * 1e3, 3))
        if self.lora_registry is not None:
            self.lora_registry.on_waiting(lora_id)

    def abort(self, request_id: str) -> None:
        seq = self.seqs.pop(request_id, None)
        if seq is None:
            return
        self.flight.finish(request_id, event="aborted", status="aborted",
                           generated=seq.num_generated)
        if seq.slot >= 0:
            self.running[seq.slot] = None
            if self.lora_registry is not None:
                self.lora_registry.on_finished(seq.lora_id)
        elif self.lora_registry is not None and seq.lora_id:
            # aborted while queued: rewind the waiting counter
            if self.lora_registry.waiting.get(seq.lora_id, 0) > 0:
                self.lora_registry.waiting[seq.lora_id] -= 1
        try:
            self.waitq[seq.rank].remove(seq)
        except ValueError:
            pass
        self._free_seq(seq)

    def drain_latency_trace(self) -> list[dict]:
        """Return + clear the accumulated predictor training rows.

        popleft-until-empty: atomic per element, so concurrent appends from the
        engine thread are neither dropped nor do they break iteration."""
        rows: list[dict] = []
        while True:
            try:
                rows.append(self.latency_trace.popleft())
            except IndexError:
                return rows

    def has_work(self) -> bool:
        return (any(self.waitq) or any(s is not None for s in self.running)
                or bool(self._pending_decode))

    # ------------------------------------------------------- scheduling core
    def _free_seq(self, seq: Sequence) -> None:
        alloc = self.allocs[seq.rank]
        for pid in seq.pages:
            alloc.release(pid)
        seq.pages = []

    def _try_admit(self) -> None:
        """Move waiting → running while slots + pages allow; reuse cached prefixes.

        Each DP rank admits independently (own queue, own batch-slot range, own
        page partition) — a saturated rank never head-of-line-blocks another."""
        for rank in range(self.num_ranks):
            self._try_admit_rank(rank)

    def _try_admit_rank(self, rank: int) -> None:
        waiting = self.waitq[rank]
        alloc = self.allocs[rank]
        lo = rank * self.slots_per_rank
        hi = lo + self.slots_per_rank
        while waiting:
            slot = next((i for i in range(lo, hi) if self.running[i] is None), None)
            if slot is None:
                return
            seq = waiting[0]
            if seq.pages:
                # a waiting seq must own nothing — preemption empties the
                # ledger via _free_seq. Anything still here is a scheduling
                # bug's strays, and they must release BEFORE the capacity
                # check below: strays hold refs, so a starved pool would
                # otherwise head-of-line block on the very pages the head
                # seq itself is leaking.
                self._free_seq(seq)
            ps = self.cfg.page_size
            # prefix-cache lookup over complete prompt blocks
            from llmd_tpu.core.kv_events import block_keys_for_tokens

            keys = block_keys_for_tokens(seq.token_ids[: seq.prompt_len], ps,
                                         seq.lora_key, seq.mm_hashes())
            hit_pages = alloc.match_prefix(keys) if self.cfg.enable_prefix_caching else []
            # never reuse the whole prompt — the final token's logits must be computed
            max_reuse = max(0, (seq.prompt_len - 1) // ps)
            hit_pages = hit_pages[:max_reuse]
            # tiered continuation: blocks evicted from HBM may live on in CPU/FS
            n_offload = 0
            if self.offload is not None and len(hit_pages) < max_reuse:
                n_offload = self.offload.match_suffix(keys[len(hit_pages) : max_reuse])
            # ...and past the native tiers, the out-of-tree connector's engine
            n_conn = 0
            if self.kv_connector is not None and len(hit_pages) + n_offload < max_reuse:
                n_conn = self.kv_connector.get_num_matched_blocks(
                    keys[len(hit_pages) + n_offload : max_reuse])

            need_new = (min(seq.prompt_len + 1, self.cfg.max_pages_per_seq * ps) + ps - 1) // ps - len(hit_pages)
            # acquire_cached pulls hit pages out of the evictable LRU, so they stop
            # counting toward num_free — admission must budget num_free minus those
            # pages or a request can consume the pool with its own hits and livelock.
            hits_in_lru = sum(
                1 for pid in hit_pages
                if (info := alloc.pages.get(pid)) is not None and info.refs == 0
            )
            if need_new > alloc.num_pages:
                # can never fit (prompt + generated tokens outgrew the pool, e.g. after
                # a preemption late in generation): finish with length, don't starve
                waiting.popleft()
                seq.finished = True
                seq.finish_reason = "length"
                self.seqs.pop(seq.request_id, None)
                self.flight.finish(seq.request_id, event="retired",
                                   reason="length", generated=seq.num_generated)
                self._outputs.append(EngineOutput(
                    request_id=seq.request_id, new_token_ids=[], finished=True,
                    finish_reason="length", prompt_len=seq.prompt_len,
                ))
                continue
            if alloc.num_free - hits_in_lru < need_new:
                return  # head-of-line blocks; FCFS admission (within this rank)
            for pid in hit_pages:
                alloc.acquire_cached(pid)
            n_hbm = len(hit_pages)
            off_pages = self._reload_offloaded(seq, keys, n_hbm, n_offload)
            conn_pages: list[int] = []
            if n_conn > 0 and len(off_pages) == n_offload:
                conn_pages = self._load_from_connector(
                    seq, keys, n_hbm + len(off_pages), n_conn)
            seq.pages = list(hit_pages) + off_pages + conn_pages
            seq.block_hashes = keys[: n_hbm + len(off_pages) + len(conn_pages)]
            seq.num_computed = (n_hbm + len(off_pages) + len(conn_pages)) * ps
            seq.num_cached_prompt = seq.num_computed
            # prefix-cache effectiveness: the hit data always existed here but
            # never reached /metrics (cached tokens / prompt tokens, plus a
            # cumulative hit-ratio gauge)
            self._prefix_cached_total += seq.num_cached_prompt
            self._prefix_prompt_total += seq.prompt_len
            self.metrics.prefix_cached_tokens.inc(seq.num_cached_prompt)
            self.metrics.prefix_prompt_tokens.inc(seq.prompt_len)
            self.metrics.prefix_hit_ratio.set(
                self._prefix_cached_total / max(1, self._prefix_prompt_total))
            if seq.admit_features is not None:
                seq.admit_features["prefix_match_pct"] = (
                    seq.num_cached_prompt / max(1, seq.prompt_len))
            seq.slot = slot
            self.running[slot] = seq
            waiting.popleft()
            self.flight.record(seq.request_id, "admitted", slot=slot,
                               rank=rank, cached_tokens=seq.num_cached_prompt,
                               pages=len(seq.pages))
            if self.lora_registry is not None:
                self.lora_registry.on_running(seq.lora_id)

    def _reload_offloaded(self, seq: Sequence, keys: list[int], n_hbm: int,
                          n_offload: int) -> list[int]:
        """Pull CPU/FS-tier blocks back into freshly allocated HBM pages and
        re-index them (they emit BlockStored gpu again — they're resident now)."""
        if n_offload <= 0:
            return []
        ps = self.cfg.page_size
        off_pids: list[int] = []
        for _ in range(n_offload):
            pid = self.alloc.allocate()
            if pid is None:
                break
            off_pids.append(pid)
        if not off_pids:
            return []
        self.cache, n_loaded = self.offload.load_into_cache(
            self.cache, keys[n_hbm : n_hbm + len(off_pids)], off_pids,
            request_id=seq.request_id,
        )
        for pid in off_pids[n_loaded:]:  # block vanished mid-way (FS evictor race)
            self.alloc.release(pid)
        off_pids = off_pids[:n_loaded]
        for i, pid in enumerate(off_pids):
            bi = n_hbm + i
            chunk = seq.token_ids[bi * ps : (bi + 1) * ps]
            parent = keys[bi - 1] if bi > 0 else None
            self.alloc.commit_block(pid, keys[bi], chunk, parent, seq.lora_key)
        self.stats.total_offload_loads += len(off_pids)
        return off_pids

    def _load_from_connector(self, seq: Sequence, keys: list[int], start: int,
                             n_conn: int) -> list[int]:
        """Pull blocks from the out-of-tree connector's engine into fresh HBM
        pages and commit them as prefix-cache entries (K5 load path)."""
        ps = self.cfg.page_size
        pids: list[int] = []
        for _ in range(n_conn):
            pid = self.alloc.allocate()
            if pid is None:
                break
            pids.append(pid)
        if not pids:
            return []
        self.cache, n_loaded = self.kv_connector.load_blocks(
            self.cache, keys[start : start + len(pids)], pids, self.cfg.num_pages)
        for pid in pids[n_loaded:]:  # external engine lost the tail meanwhile
            self.alloc.release(pid)
        pids = pids[:n_loaded]
        for i, pid in enumerate(pids):
            bi = start + i
            chunk = seq.token_ids[bi * ps : (bi + 1) * ps]
            parent = keys[bi - 1] if bi > 0 else None
            self.alloc.commit_block(pid, keys[bi], chunk, parent, seq.lora_key)
        return pids

    def _ensure_pages(self, seq: Sequence, upto_tokens: int) -> bool:
        ps = self.cfg.page_size
        need = (upto_tokens + ps - 1) // ps
        alloc = self.allocs[seq.rank]
        while len(seq.pages) < need:
            pid = alloc.allocate()
            if pid is None:
                self.metrics.kv_exhaustion.inc()
                return False
            seq.pages.append(pid)
        return True

    def _finish_if_outgrew_pool(self, seq: Sequence) -> None:
        """Termination backstop for a RUNNING seq that can never be scheduled
        again: its next token needs more pages than the rank's ENTIRE pool
        (generation outgrew the pool with nothing left to evict). Without
        this the step loop spins forever — plan empty, has_work() true —
        because the admission-path 'can never fit → finish length' backstop
        (see _try_admit_rank) only reaches seqs that went back to the waitq.
        Mirrors its semantics: finish with 'length', deliver what we have."""
        ps = self.cfg.page_size
        if (len(seq.token_ids) + ps - 1) // ps <= self.allocs[seq.rank].num_pages:
            return  # transient pressure: another seq's retirement will free pages
        self._retire(seq, "length")
        self._outputs.append(EngineOutput(
            request_id=seq.request_id, new_token_ids=[], finished=True,
            finish_reason="length", num_cached_prompt_tokens=seq.num_cached_prompt,
            prompt_len=seq.prompt_len,
        ))

    def _preempt_one(self, rank: int = 0,
                     exclude: Optional[Sequence] = None) -> bool:
        """Evict the rank's most recently arrived running seq back to waiting
        (recompute semantics). Pages are rank-partitioned, so only a same-rank
        victim frees memory the caller can use. ``exclude`` is the seq the
        caller is trying to schedule: evicting it frees its own pages only to
        reset it to token zero — a thrash loop, never progress."""
        # Bank any deferred first tokens BEFORE choosing a victim: a pending-
        # sample seq is idle and page-holding (a prime victim), and evicting
        # it would drop its un-applied token — full re-prefill, re-defer,
        # re-evict, a tight-pool ping-pong with zero forward progress. The
        # flush makes per-seq progress monotonic again (the recompute path
        # preserves applied tokens); preemption is the rare slow path, so the
        # extra device read here is noise.
        self._flush_pending_sample()
        victims = [s for s in self.running
                   if s is not None and s.rank == rank and s is not exclude]
        if not victims:
            return False
        victim = max(victims, key=lambda s: s.arrival_time)
        self.running[victim.slot] = None
        victim.slot = -1
        if self.lora_registry is not None:  # back to waiting: keep counters true
            self.lora_registry.on_finished(victim.lora_id)
            self.lora_registry.on_waiting(victim.lora_id)
        self._free_seq(victim)
        victim.num_computed = 0
        victim.block_hashes = []
        victim.num_cached_prompt = 0
        self.waitq[rank].appendleft(victim)
        self.stats.total_preemptions += 1
        self.metrics.preemptions.inc()
        self.flight.record(victim.request_id, "preempted", rank=rank,
                           generated=victim.num_generated)
        return True

    # --------------------------------------------------------------- stepping
    def step(self) -> list[EngineOutput]:
        """One engine iteration: admit, then run the first eligible step
        program (engine/programs.py registration order: unified while any
        sequence is prefilling or a constrained row needs the unified
        degrade, speculative verify when spec_mode="ngram", fused decode
        otherwise)."""
        self._outputs = []
        if self.offload is not None:
            self._offload_drain()
        self._try_admit()
        self.programs.route(self).run(self)
        self.stats.num_waiting = sum(len(q) for q in self.waitq)
        self.stats.num_running = sum(1 for s in self.running if s is not None)
        self.stats.kv_utilization = (
            sum(a.num_active for a in self.allocs) / max(1, self.cfg.num_pages))
        m = self.metrics
        m.requests_waiting.set(self.stats.num_waiting)
        m.requests_running.set(self.stats.num_running)
        m.kv_usage.set(self.stats.kv_utilization)
        m.batch_occupancy.labels(kind="running").observe(self.stats.num_running)
        m.batch_occupancy.labels(kind="waiting").observe(self.stats.num_waiting)
        if self._eplb is not None:
            self._eplb_tick()
        return self._outputs

    # ------------------------------------------------- step-program run hooks
    # Eligibility predicates + run hooks for the routable registry entries.
    # route() calls them unbound (spec.eligible(engine) / spec.run(engine)),
    # so a custom program registered by a test or a future subsystem can pass
    # any callable of the same shape — adding a program is one registry entry.

    def _unified_eligible(self) -> bool:
        """The unified mixed step serves prefill chunks, and remains the
        1-token degrade for constrained rows the dense-table scheme can't
        express (structured_fused_decode off, a row combining grammar AND
        logit_bias, or tables past the structured_table_max_elems gate)."""
        if self._prefilling_seqs():
            return True
        return (any(s is not None and (s.structured is not None or s.logit_bias)
                    for s in self.running)
                and self._constrained_needs_unified())

    def _run_unified_program(self) -> None:
        # the mixed step reads host token state — apply any in-flight decode first
        self._flush_pending_decode()
        self._step_unified()

    def _run_verify_program(self) -> None:
        # decode/verify build their batch from host token state: the deferred
        # prefill sample (first tokens) must land first
        self._flush_pending_sample()
        # a verify step replaces this step's fused decode call when
        # prompt-lookup drafts exist; otherwise fall through to fused decode
        if not self._spec_try_verify():
            self._step_decode()

    def _run_decode_program(self) -> None:
        self._flush_pending_sample()
        self._step_decode()

    def _emit_step_spans(self, phase: str, seqs: list[Sequence],
                         start_ns: int, batch_size: int, n_tokens: int) -> None:
        """Emit one `engine.step` child span per traced sequence in the batch,
        parented on the request span context carried in via add_request — the
        engine's step work shows up nested under `engine.generate`."""
        tracer = self.tracer
        if tracer is None:
            return
        for s in seqs:
            ctx = s.trace_ctx
            if ctx is None or not getattr(ctx, "sampled", False):
                continue
            span = tracer.start_span(
                "engine.step", parent=ctx,
                **{"llm_d.phase": phase, "llm_d.batch_size": batch_size,
                   "llm_d.step_tokens": n_tokens,
                   "llm_d.request_id": s.request_id})
            span.start_ns = start_ns
            span.end()

    def _trace_exemplar(self, seqs) -> Optional[dict]:
        """OpenMetrics exemplar labels from the first traced seq in a batch —
        feeds the step-duration histogram so a slow bucket links to a trace."""
        for s in seqs:
            ctx = s.trace_ctx
            if ctx is not None and getattr(ctx, "trace_id", ""):
                return {"trace_id": ctx.trace_id}
        return None

    def _offload_drain(self) -> None:
        """Keep the plain free list above the watermark by batch-demoting the oldest
        LRU pages (one gather per step) — evictions then rarely hit the per-page
        on_evict backstop inside allocate()."""
        need = self.cfg.offload_watermark_pages - len(self.alloc.free)
        if need <= 0 or not self.alloc.lru:
            return
        n = min(need, self.cfg.offload_staging_blocks, len(self.alloc.lru))
        pairs = self.alloc.demote_lru(n)
        self.offload.demote_batch(self.cache, pairs)

    def _prefill_target(self, seq: Sequence) -> int:
        """Tokens that must be processed chunk-wise before decode can take over.

        Fresh sequence: the whole prompt (last logits sample the first token).
        Preempted-with-generated-tokens: recompute through len-1; the decode path then
        feeds the final token and continues sampling (recompute semantics).
        """
        if len(seq.token_ids) == seq.prompt_len:
            return seq.prompt_len
        return len(seq.token_ids) - 1

    def _prefilling_seqs(self) -> list[Sequence]:
        cands = [
            s for s in self.running
            if s is not None and s.num_computed < self._prefill_target(s)
        ]
        return sorted(cands, key=lambda s: s.arrival_time)

    def _decode_ready(self) -> list[Sequence]:
        return [
            s for s in self.running
            if s is not None and s.num_computed == len(s.token_ids) - 1
            and s.num_computed >= s.prompt_len
        ]

    @_profile_phase("llmd.unified")
    def _step_unified(self) -> None:
        """Pack decode tokens + prefill chunks (across sequences) into the flat
        token budget and run ONE compiled step."""
        t0 = time.perf_counter()
        t0_ns = time.time_ns()
        NT = self.cfg.batched_tokens
        B = self.cfg.max_batch_size
        R = self.num_ranks
        # per-rank token budgets (the reference's per-rank-engine
        # --max-num-batched-tokens); single-rank engines keep the whole budget
        budgets = [NT // R] * R

        # decode rows first (keeps TPOT low while prompts stream in), then
        # prefill chunks oldest-first
        plan: list[tuple[Sequence, int, bool]] = []  # (seq, q_len, is_decode)
        for s in self._decode_ready():
            if len(plan) >= B:
                break
            if s.slot < 0:
                # preempted while packing an earlier row: the snapshot is
                # stale. Without this guard the zombie's _ensure_pages can
                # re-acquire pages onto a seq whose ledger _free_seq already
                # emptied — pages it carries into the waitq and leaks at
                # re-admission (measured: 4 pages/occurrence → pool exhaustion
                # → self-preempt livelock in tight pools)
                continue
            if budgets[s.rank] <= 0:
                continue
            if not self._ensure_pages(s, len(s.token_ids)):
                if not self._preempt_one(s.rank, exclude=s) or s.slot < 0:
                    self._finish_if_outgrew_pool(s)
                    continue
                if not self._ensure_pages(s, len(s.token_ids)):
                    continue
            plan.append((s, 1, True))
            budgets[s.rank] -= 1
        for s in self._prefilling_seqs():
            if len(plan) >= B:
                break
            if s.slot < 0:
                continue  # preempted while packing decode rows
            n = min(self.cfg.prefill_chunk, self._prefill_target(s) - s.num_computed,
                    budgets[s.rank])
            if n <= 0:
                continue
            if not self._ensure_pages(s, s.num_computed + n):
                if not self._preempt_one(s.rank, exclude=s) or s.slot < 0:
                    self._finish_if_outgrew_pool(s)
                    continue
                if not self._ensure_pages(s, s.num_computed + n):
                    continue
            plan.append((s, n, False))
            budgets[s.rank] -= n
        plan = [(s, n, d) for (s, n, d) in plan if s.slot >= 0]
        if not plan:
            # nothing schedulable — a deferred sample may be WHY (its rows
            # hold slots/pages until applied, and an apply can retire): flush
            # it so the next step can make progress instead of spinning
            self._flush_pending_sample()
            return

        toks = np.zeros((NT,), np.int32)
        pos = np.full((NT,), -1, np.int32)
        sids = np.zeros((NT,), np.int32)
        lora_tok = np.zeros((NT,), np.int32)
        pts = np.full((B, self.cfg.max_pages_per_seq), -1, np.int32)
        lens = np.ones((B,), np.int32)
        cu = np.zeros((B + 1,), np.int32)
        # only pay the mm staging buffers when this step actually carries media
        # prefill rows (text-only steps on a VL model jit a no-mm variant)
        is_vl = self.model_cfg.mm_tokens > 0 and any(
            s.mm_items and not is_decode for s, _, is_decode in plan)
        if is_vl:
            # row-aligned with the flat token batch: mm_embeds[i] replaces the
            # embedding of tokens[i] where mm_mask[i] (encode-stage injection)
            mm_embeds = np.zeros((NT, self.model_cfg.hidden_size), np.float32)
            mm_mask = np.zeros((NT,), np.bool_)
        off = 0
        for i, (s, n, is_decode) in enumerate(plan):
            start = len(s.token_ids) - 1 if is_decode else s.num_computed
            toks[off : off + n] = s.token_ids[start : start + n]
            pos[off : off + n] = np.arange(start, start + n)
            sids[off : off + n] = i
            lora_tok[off : off + n] = self._lora_slot(s)
            pts[i, : len(s.pages)] = s.pages
            lens[i] = start + n
            if is_vl and s.mm_items and not is_decode:
                ph = self.model_cfg.mm_placeholder_id
                k = self.model_cfg.mm_tokens
                occ = sum(1 for t in s.token_ids[:start] if t == ph)
                for j in range(n):
                    if s.token_ids[start + j] == ph:
                        item, row = occ // k, occ % k
                        if item < len(s.mm_items):
                            mm_embeds[off + j] = s.mm_items[item][1][row]
                            mm_mask[off + j] = True
                        occ += 1
            off += n
            cu[i + 1] = off
        cu[len(plan) + 1 :] = off

        t1 = time.perf_counter()
        mm_args = ((jnp.asarray(mm_embeds), jnp.asarray(mm_mask)) if is_vl else ())
        # ring-eligible: ONE fresh self-contained prefill chunk at offset 0
        # (positions 0..n-1, no prior KV) — the only regime where causality by
        # row index equals causality by position and in-chunk q/k/v are the
        # whole attention problem (see make_ring_attn_impl)
        step_fn, step_prog = self._unified_fn, "unified"
        if (self._unified_ring_fn is not None and len(plan) == 1
                and not plan[0][2] and plan[0][0].num_computed == 0
                and pos[0] == 0 and not is_vl):
            step_fn, step_prog = self._unified_ring_fn, "unified_ring"
            self.stats.n_ring_prefill_steps += 1
        # synchronous program: the postprocess below consumes the logits this
        # same step, so dispatch and completion are recorded together
        self.programs.record_dispatch(step_prog)
        self.programs.record_complete(step_prog)
        logits, self.cache, cnt, moe_drop = step_fn(
            self._run_params(), self.cache, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(sids), jnp.asarray(pts), jnp.asarray(lens), jnp.asarray(cu),
            jnp.asarray([len(plan)], jnp.int32), jnp.asarray(lora_tok), *mm_args,
        )
        if self.cfg.instrument:
            # llmd-lint: allow[hot-host-sync] instrument-gated timing barrier; off in production serving
            logits.block_until_ready()
        t2 = time.perf_counter()
        if self._eplb is not None:
            self._eplb_record(cnt)
        if self.model_cfg.is_moe:
            self._moe_record_dropped(moe_drop)

        # goodput classification reads pre-postprocess sequence state: the
        # first-chunk prefix credit (num_computed == num_cached_prompt only
        # holds before the loop advances num_computed) and re-prefill
        # detection (a prefill chunk on a seq carrying generated tokens is
        # recompute of preempted work, not fresh compute)
        util_saved = util_recompute = 0
        if self.util is not None:
            for s, n, is_decode in plan:
                if not is_decode:
                    if (s.num_computed == s.num_cached_prompt
                            and s.num_cached_prompt):
                        util_saved += s.num_cached_prompt
                    if len(s.token_ids) > s.prompt_len:
                        util_recompute += n

        sample_list: list[tuple[int, Sequence]] = []  # (batch row, seq)
        has_decode_rows = False
        for i, (s, n, is_decode) in enumerate(plan):
            if is_decode:
                s.num_computed = len(s.token_ids)
                s.maybe_commit_blocks(self.allocs[s.rank])
                self.stats.total_decode_tokens += 1
                sample_list.append((i, s))
                has_decode_rows = True
            else:
                if s.num_computed == s.num_cached_prompt:
                    # first chunk of a (re)prefill — cached==computed only holds
                    # before any chunk lands (and again after preemption resets)
                    self.flight.record(s.request_id, "prefill_start",
                                       cached_tokens=s.num_cached_prompt)
                s.num_computed += n
                s.maybe_commit_blocks(self.allocs[s.rank])
                self.stats.total_prefill_tokens += n
                if s.num_computed >= self._prefill_target(s):
                    self.flight.record(s.request_id, "prefill_end",
                                       prefill_tokens=s.num_computed)
                if (len(s.token_ids) == s.prompt_len
                        and s.num_computed == s.prompt_len):
                    # fresh prefill complete: sample first token from last logits
                    sample_list.append((i, s))
        # Pipelined sample read: dispatch this step's sampling (device-chained
        # on step_fn), apply the PREVIOUS step's deferred sample while the
        # device runs, and defer this one — its rows are unschedulable until
        # applied (not prefilling: num_computed==target; not decode-ready:
        # num_computed==len(token_ids)), so the next plan can't race them.
        # Mixed steps with decode rows apply synchronously: a deferred decode
        # row would sit out the following step, stalling steady-state ITL.
        prev, self._pending_sample = self._pending_sample, None
        bias = self._build_bias(sample_list, logits.shape) if sample_list else None
        rec = (self._sample_dispatch(sample_list, logits, bias=bias)
               if sample_list else None)
        if prev is not None:
            self._sample_apply(prev)
        if rec is not None:
            if self.cfg.pipeline_prefill_sample and not has_decode_rows:
                self._pending_sample = rec
            else:
                self._sample_apply(rec)
        t3 = time.perf_counter()
        st = self.stats
        st.time_host_pack += t1 - t0
        st.time_device += t2 - t1
        st.time_postprocess += t3 - t2
        st.time_prefill_steps += t3 - t0
        st.n_unified_steps += 1
        n_dec = sum(1 for _, _, d in plan if d)
        n_pre = sum(n for _, n, d in plan if not d)
        if n_dec:
            self.metrics.decode_tokens.inc(n_dec)
        if n_pre:
            self.metrics.prefill_tokens.inc(n_pre)
        self.metrics.step_duration.labels(phase="unified").observe(
            t3 - t0, exemplar=self._trace_exemplar([s for s, _, _ in plan]))
        if self.util is not None:
            # analytic cost from the PACKED shape: the program computes all
            # NT positions (padding included); KV reads ≈ one pass over each
            # row's resident KV (exact for decode rows, a lower bound for
            # chunked prefill), writes = the real positions landed
            cost = self.util.cost(
                step_prog, slot_tokens=NT, weight_passes=1,
                kv_read_tokens=int(lens[: len(plan)].sum()),
                kv_write_tokens=off)
            self.util.record(
                step_prog, cost, t3 - t0,
                committed=n_dec + n_pre - util_recompute,
                preempted_recompute=util_recompute,
                prefix_saved=util_saved,
                compile_counts=self.programs.compile_counts())
        self._emit_step_spans("unified", [s for s, _, _ in plan], t0_ns,
                              len(plan), n_pre + n_dec)

    def _step_decode(self) -> None:
        """Fused multi-step decode with pipelined dispatch.

        The tunnel/PCIe round-trip for reading sampled tokens is the dominant
        serving overhead off-device (measured ~69 ms through the dev tunnel, and
        real on any host): with ``cfg.pipeline_decode`` the host dispatches call
        N+1 chained on call N's *device-resident* last tokens, then reads call
        N's results while N+1 runs — vLLM's async output processing, XLA-style.
        The chain holds only while the active set is unchanged; any membership
        change (finish, preemption, new prefill) flushes first.
        """
        t0 = time.perf_counter()
        active = self._decode_ready()
        if not active:
            self._flush_pending_decode()
            return
        B = self.cfg.max_batch_size
        k = max(1, self.cfg.decode_steps)
        q = self._pending_decode
        off = sum(rec["k"] for rec in q)

        # The host knows every row's HARD budget (max_tokens / max_model_len)
        # without any device read: if the steps already in flight cover it for
        # every row, one more speculative call would run k scan steps of
        # fully-masked compute — measured as 2 wasted calls (64 of 192
        # step-slots) per request wave at OSL 128 / k=32. Drain the oldest
        # call instead; its results change membership and the normal flush
        # path takes over. Checked BEFORE _ensure_pages so a provably-useless
        # call cannot demand pages (or degrade to a unified step) either.
        # (EOS-before-budget still speculates — that is the pipeline's
        # purpose; this clamp only removes provably-useless calls.)
        if q:
            horizon = max(
                min(s.max_tokens - (len(s.token_ids) + off - s.prompt_len),
                    self.cfg.max_model_len - (len(s.token_ids) + off))
                for s in active)
            if horizon <= 0:
                self._decode_process(q.pop(0))
                return

        # A k-step scan writes KV for positions len-1 .. len+off+k-2 → needs
        # len+off+k-1 slots. If the pool can't cover the horizon, flush and
        # degrade to a single unified step (decode rows only) rather than
        # preempting sequences that could progress.
        ok = all(
            self._ensure_pages(
                s, min(len(s.token_ids) + off + k - 1, self.cfg.max_model_len))
            for s in active if s.slot >= 0
        )
        if not ok:
            self._flush_pending_decode()
            self._step_unified()
            return
        active = [s for s in active if s.slot >= 0]
        if not active:
            return
        if (any(s.structured is not None or s.logit_bias for s in active)
                and self._plan_chain_masks(active) is None):
            # raced out of fused-mask eligibility (a preemption above changed
            # the batch): degrade like the pool-pressure path rather than
            # letting a constrained row decode unmasked
            self._flush_pending_decode()
            self._step_unified()
            return

        if q:
            same = {(s.request_id, s.slot) for s in active} == {
                (s.request_id, slot) for s, slot in q[-1]["rows"]}
            if same and self.cfg.pipeline_decode:
                rec = self._decode_dispatch(active, k, chain=q[-1], wall_start=t0,
                                            off=off)
                q.append(rec)
                # keep up to pipeline_depth calls in flight: the queued call
                # behind the running one lets the device go back-to-back while
                # the finished call's tokens cross back to the host
                if len(q) > max(1, self.cfg.pipeline_depth):
                    self._decode_process(q.pop(0))
                return
            self._flush_pending_decode()
            q = self._pending_decode  # flush rebinds the queue — drop the stale ref
            active = [s for s in self._decode_ready() if s.slot >= 0]
            if not active:
                return
        rec = self._decode_dispatch(active, k, chain=None, wall_start=t0)
        if self.cfg.pipeline_decode:
            q.append(rec)
        else:
            self._decode_process(rec)

    def _flush_pending_decode(self) -> None:
        q, self._pending_decode = self._pending_decode, []
        for rec in q:
            self._decode_process(rec)
        if q:
            # one event per chain teardown (the admission/retire boundary
            # where the host re-enters the loop); a system event, not a
            # per-request one — the chain is batch-scoped, and its lead row
            # may have retired during this very drain (`retired` must stay
            # the terminal event on every request timeline)
            s, _slot = q[-1]["rows"][0]
            self.flight.record_system("chain_retire", calls=len(q),
                                      lead_request=s.request_id)

    # ------------------------------------------------------------ speculation
    def _verify_nt(self) -> int:
        """Static packed width of the verify programs. Every draft is clamped
        to ``spec_tokens`` (``_spec_propose``), so ``max_batch_size *
        (spec_tokens + 1)`` positions always hold the worst-case plan —
        padding verify to the full prefill width (``batched_tokens``) would
        pay a prefill-sized forward to land a handful of tokens per row
        (6.4x waste at the tiny smoke shape: 40 real positions in NT=256)."""
        return min(self.cfg.batched_tokens,
                   self.cfg.max_batch_size * (self.cfg.spec_tokens + 1))

    def _spec_propose(self, s: Sequence, max_draft: int) -> list[int]:
        """Prompt-lookup draft for one decode-ready seq, clamped so the
        verify step can land every accepted token: k drafts + 1 bonus token
        may append, so k is bounded by the remaining max_tokens /
        max_model_len budget minus one (the bonus token is the plain-decode
        token and is always in budget). Constrained rows draft too
        (spec × structured compose, PERF.md Lever 13): their proposal is
        trimmed to its longest constraint-legal prefix, so the masked verify
        program only ever checks tokens the grammar could emit."""
        k = min(self.cfg.spec_tokens, max_draft,
                s.max_tokens - s.num_generated - 1,
                self.cfg.max_model_len - len(s.token_ids) - 1)
        if k <= 0:
            return []
        draft = propose_ngram_draft(s.token_ids, k, self.cfg.spec_ngram_max,
                                    self.cfg.spec_ngram_min)[:k]
        if draft and (s.structured is not None or s.logit_bias):
            draft = self._spec_filter_draft(s, draft)
        return draft

    def _spec_filter_draft(self, s: Sequence, draft: list[int]) -> list[int]:
        """FSM-aware draft truncation for a constrained row: keep the longest
        prefix of ``draft`` its constraint allows. Grammar rows walk the host
        automaton from the synced cursor (an idempotent ``sync`` first — the
        cursor must reflect every committed token before extrapolating);
        logit_bias rows cut at the first effectively-banned token. Returns []
        when spec_structured is off (legacy: constrained rows never draft)."""
        if not self.cfg.spec_structured:
            return []
        stt = s.structured
        if stt is not None:
            fresh = stt.sync(s.token_ids, s.prompt_len)
            if fresh:
                self.stats.structured_violations += fresh
                self.metrics.structured_violations.inc(fresh)
            return draft[:stt.grammar.legal_prefix_len(stt.state, draft)]
        for i, t in enumerate(draft):
            if s.logit_bias.get(t, 0.0) <= -100.0:
                return draft[:i]
        return draft

    def _spec_try_verify(self) -> bool:
        """Decode-path speculation gate; True = a verify step ran (replacing
        this step's fused decode call).

        Probes the drafter on the current host view first: while pipelined
        fused calls are in flight that view is stale, but a stale no-match is
        a cheap signal to keep the pipelined decode path (non-echo workloads
        keep their dispatch chain). Only a positive probe pays the flush;
        drafts are then re-proposed on the landed state. After the flush the
        decode horizon is read from live ``len(token_ids)``, so the next
        fused call's clamp accounts for accepted-token jumps automatically.
        """
        active = self._decode_ready()
        if not active:
            return False
        # Constrained rows ride verify ONLY through the masked verify program
        # (grammar bias + FSM advance fused per packed position). When the
        # compose knob is off, or the batch's mask plan is inexpressible as
        # dense tables (combined grammar+bias row, table-size gate), the
        # batch falls back to the fused decode path, which has its own
        # masked/degrade handling.
        if any(s.structured is not None or s.logit_bias for s in active):
            if not (self.cfg.spec_structured
                    and self._plan_chain_masks(active) is not None):
                return False
        # Greedy acceptance is only bitwise-equivalent to sequential decoding
        # for greedy rows; a batch with sampled sequences falls back to the
        # fused decode path.
        if any(s.sampling.temperature > 0.0 for s in active):
            return False
        # Probe arming (per sequence): the drafter is a pure function of each
        # row's token history, so a no-match verdict stays valid until fresh
        # tokens land for that row (_decode_process / _sample_apply / a
        # verify step re-arm it). Skipping the re-probe drops the per-step
        # O(context) numpy scans from the chained steady state — and one
        # non-repetitive row no longer disarms the rest of the batch.
        probed = False
        for s in active:
            if s.spec_armed:
                if self._spec_propose(s, self.cfg.spec_tokens):
                    probed = True
                else:
                    s.spec_armed = False
                    s.spec_flips += 1
        if not probed:
            return False
        self._flush_pending_decode()
        active = [s for s in self._decode_ready() if s.slot >= 0]
        if not active:
            return True  # the flush retired/changed the batch; step done
        NT = self._verify_nt()
        R = self.num_ranks
        # every active row is guaranteed its plain token (batched_tokens >=
        # max_batch_size); drafts share the leftover per-rank budget
        spare = [NT // R] * R
        for s in active:
            spare[s.rank] -= 1
        plan: list[tuple[Sequence, list[int]]] = []
        for s in active:
            if len(plan) >= self.cfg.max_batch_size:
                break
            if s.slot < 0:
                continue  # preempted while packing an earlier row
            draft = (self._spec_propose(s, max(0, spare[s.rank]))
                     if s.spec_armed else [])
            if draft and not self._ensure_pages(s, len(s.token_ids) + len(draft)):
                draft = []  # shed the draft before shedding a sequence
            if not self._ensure_pages(s, len(s.token_ids)):
                if not self._preempt_one(s.rank, exclude=s) or s.slot < 0:
                    self._finish_if_outgrew_pool(s)
                    continue
                if not self._ensure_pages(s, len(s.token_ids)):
                    continue
            plan.append((s, draft))
            spare[s.rank] -= len(draft)
        plan = [(s, d) for s, d in plan if s.slot >= 0]
        if any(s.structured is not None or s.logit_bias for s, _ in plan):
            # a constrained row may have become decode-ready during the flush:
            # re-check masked-verify eligibility on the FINAL plan — an
            # ineligible row must never ride the unmasked verify program
            if not (self.cfg.spec_structured and self._plan_chain_masks(
                    [s for s, _ in plan]) is not None):
                return False
        if not any(d for _, d in plan):
            # fresh state proposes nothing: plain decode instead — and no
            # re-probe for these rows until the next landing changes that
            for s, _ in plan:
                if s.spec_armed:
                    s.spec_armed = False
                    s.spec_flips += 1
            return False
        self._step_spec_verify(plan)
        return True

    @_profile_phase("llmd.spec_verify")
    def _step_spec_verify(self, plan: list[tuple[Sequence, list[int]]]) -> None:
        """Pack each sequence's draft as a short self-contained chunk (its
        last real token + the draft) through the verify program, accept the
        longest greedy-matching prefix plus one bonus token, and roll back
        the rejected tail — host token state never contains a draft token
        unless verification proved it, so ``maybe_commit_blocks`` can never
        commit an unverified page, and surplus draft pages release straight
        back to the allocator's free list."""
        t0 = time.perf_counter()
        t0_ns = time.time_ns()
        NT = self._verify_nt()
        B = self.cfg.max_batch_size
        toks = np.zeros((NT,), np.int32)
        pos = np.full((NT,), -1, np.int32)
        sids = np.zeros((NT,), np.int32)
        lora_tok = np.zeros((NT,), np.int32)
        pts = np.full((B, self.cfg.max_pages_per_seq), -1, np.int32)
        lens = np.ones((B,), np.int32)
        cu = np.zeros((B + 1,), np.int32)
        off = 0
        rows: list[tuple[Sequence, list[int], int, int]] = []
        for i, (s, draft) in enumerate(plan):
            start = len(s.token_ids) - 1
            chunk = [s.token_ids[-1]] + draft
            n = len(chunk)
            toks[off : off + n] = chunk
            pos[off : off + n] = np.arange(start, start + n)
            sids[off : off + n] = i
            lora_tok[off : off + n] = self._lora_slot(s)
            pts[i, : len(s.pages)] = s.pages
            lens[i] = start + n
            if draft:
                s.spec_drafted += len(draft)
                self.stats.spec_drafted += len(draft)
                if s.structured is not None or s.logit_bias:
                    self.stats.spec_drafted_constrained += len(draft)
                self.metrics.spec_drafted.inc(len(draft))
                self.flight.record(s.request_id, "spec_draft",
                                   drafted=len(draft))
            rows.append((s, draft, off, s.slot))
            off += n
            cu[i + 1] = off
        cu[len(plan) + 1 :] = off
        tm = time.perf_counter()
        # constrained rows ride the masked variant: dense [G,S,V] bias/next
        # tables + per-packed-row FSM entry states (None = no constrained
        # row). Stage wall self-accounts into time_mask_build, so the pack
        # split below stops at tm — the two stats stay disjoint.
        mask = self._spec_stage_verify_masks(plan)
        prog = "verify" if mask is None else "verify_masked"
        t1 = time.perf_counter()
        self.programs.record_dispatch(prog)
        if mask is None:
            fsm_out = None
            greedy, self.cache, cnt, moe_drop = self._verify_fn(
                self._run_params(), self.cache, jnp.asarray(toks),
                jnp.asarray(pos), jnp.asarray(sids), jnp.asarray(pts),
                jnp.asarray(lens), jnp.asarray(cu),
                jnp.asarray([len(plan)], jnp.int32), jnp.asarray(lora_tok),
            )
        else:
            greedy, fsm_out, self.cache, cnt, moe_drop = self._verify_masked_fn(
                self._run_params(), self.cache, jnp.asarray(toks),
                jnp.asarray(pos), jnp.asarray(sids), jnp.asarray(pts),
                jnp.asarray(lens), jnp.asarray(cu),
                jnp.asarray([len(plan)], jnp.int32), jnp.asarray(lora_tok),
                mask["fsm0"], mask["gidx"], mask["bias_tab"], mask["next_tab"],
            )
        # llmd-lint: allow[hot-host-sync] designed sync point: verify needs the greedy tokens on host to accept/reject the draft
        g = np.asarray(greedy)  # [NT] (device sync point)
        # llmd-lint: allow[hot-host-sync] same designed sync point: the per-position FSM states ride the readback the greedy tokens already paid for
        fsm = np.asarray(fsm_out) if fsm_out is not None else None
        self.programs.record_complete(prog)
        t2 = time.perf_counter()
        if self._eplb is not None:
            self._eplb_record(cnt)
        if self.model_cfg.is_moe:
            self._moe_record_dropped(moe_drop)
        now = time.monotonic()
        spec_rej0 = self.stats.spec_rejected
        n_tokens = 0
        for s, draft, row0, slot in rows:
            if s.finished or s.slot != slot or self.running[slot] is not s:
                continue  # preempted while packing later rows
            kept: list[int] = []
            finished, reason = False, None
            # Row j's greedy token continues chunk position start+j: accept
            # drafts while they match it, append the first divergence (the
            # bonus token — exactly what sequential decode would emit).
            for j in range(len(draft) + 1):
                t = int(g[row0 + j])
                kept.append(t)
                s.token_ids.append(t)
                finished, reason = self._check_finish(s, t)
                if finished or j >= len(draft) or draft[j] != t:
                    break
            accepted = sum(1 for j, t in enumerate(kept)
                           if j < len(draft) and draft[j] == t)
            rejected = len(draft) - accepted
            # the newest token's KV is never written yet → computed = len - 1
            s.num_computed = len(s.token_ids) - 1
            if s.first_token_time is None:
                s.first_token_time = now
                self.flight.record(
                    s.request_id, "first_token",
                    ttft_ms=round((now - s.arrival_time) * 1e3, 3))
            s.maybe_commit_blocks(self.allocs[s.rank])
            self._spec_release_tail(s)
            constrained = s.structured is not None or bool(s.logit_bias)
            if fsm is not None and s.structured is not None:
                stt = s.structured
                dev_state = int(fsm[row0 + len(kept) - 1])
                if self.cfg.spec_structured_crosscheck:
                    # recovery path kept honest: re-derive the cursor on host
                    # from the accepted tokens and compare with the device
                    # state; a mismatch keeps the host value (and is a bug)
                    fresh = stt.sync(s.token_ids, s.prompt_len)
                    if fresh:
                        self.stats.structured_violations += fresh
                        self.metrics.structured_violations.inc(fresh)
                    if stt.state != dev_state:
                        self.stats.spec_fsm_crosscheck_mismatches += 1
                else:
                    # the state at the last kept position IS the
                    # post-acceptance automaton state: rejected tails rolled
                    # back for free, exactly as _spec_release_tail rolls back
                    # their KV pages. Adopt it in place of the host resync.
                    stt.state = dev_state
                    stt.n_seen = len(s.token_ids) - s.prompt_len
            s.spec_accepted += accepted
            if not s.spec_armed:
                s.spec_flips += 1
            s.spec_armed = True  # fresh tokens landed for this row: re-probe
            st = self.stats
            st.spec_accepted += accepted
            st.spec_rejected += rejected
            if constrained:
                st.spec_accepted_constrained += accepted
            st.total_decode_tokens += len(kept)
            n_tokens += len(kept)
            if accepted:
                self.metrics.spec_accepted.inc(accepted)
            if rejected:
                self.metrics.spec_rejected.inc(rejected)
            if draft:
                self.flight.record(s.request_id, "spec_verify",
                                   drafted=len(draft), accepted=accepted,
                                   n_tokens=len(kept),
                                   constrained=constrained,
                                   generated=s.num_generated)
            else:
                self.flight.record(s.request_id, "decode", n_tokens=len(kept),
                                   generated=s.num_generated)
            if finished:
                self._retire(s, reason)
            self._outputs.append(EngineOutput(
                request_id=s.request_id, new_token_ids=kept, finished=finished,
                finish_reason=reason,
                num_cached_prompt_tokens=s.num_cached_prompt,
                prompt_len=s.prompt_len,
            ))
        t3 = time.perf_counter()
        st = self.stats
        st.time_host_pack += tm - t0
        st.time_device += t2 - t1
        st.time_postprocess += t3 - t2
        st.time_spec_steps += t3 - t0
        st.n_spec_verify_steps += 1
        if n_tokens:
            self.metrics.decode_tokens.inc(n_tokens)
        self.metrics.step_duration.labels(phase="spec_verify").observe(
            t3 - t0, exemplar=self._trace_exemplar([s for s, _, _, _ in rows]))
        if self.util is not None:
            # verify burns its whole NT budget (PR 15 measured 6.4x padding
            # here — the standing padding_efficiency series); kept tokens
            # commit, rejected draft positions are the speculation waste,
            # rows preempted mid-pack fall into the padding residual
            cost = self.util.cost(
                prog, slot_tokens=NT, weight_passes=1,
                kv_read_tokens=int(lens[: len(plan)].sum()),
                kv_write_tokens=off)
            self.util.record(
                prog, cost, t3 - t0,
                committed=n_tokens,
                spec_rejected=self.stats.spec_rejected - spec_rej0,
                compile_counts=self.programs.compile_counts())
        self._emit_step_spans("spec_verify", [s for s, _, _, _ in rows], t0_ns,
                              len(plan), n_tokens)

    def _spec_release_tail(self, s: Sequence) -> None:
        """Roll back KV pages grown for rejected draft tokens: trim the page
        ledger to what the accepted length needs. Trimmed pages carry refs=1
        and no block hash (commits never cover unverified tokens), so
        ``release`` returns them straight to the free list — the r05
        page-ledger consistency invariant holds through every rollback."""
        ps = self.cfg.page_size
        need = max((len(s.token_ids) + ps - 1) // ps, len(s.block_hashes))
        alloc = self.allocs[s.rank]
        while len(s.pages) > need:
            alloc.release(s.pages.pop())

    # ------------------------------------------------- fused constrained decode
    def _plan_chain_masks(self, active: list[Sequence]) -> Optional[dict]:
        """Table-slot assignment + size gate for the fused masked decode
        program. None = this batch's constrained rows cannot ride it and must
        degrade to 1-token unified steps: the knob is off, a row combines a
        grammar AND a logit_bias (two bias sources, one table slot), or the
        padded tables would exceed structured_table_max_elems.

        Tables are shared BY GRAMMAR, not by row — G is 1 (the zero no-op
        grammar unconstrained rows index) + distinct grammars + one slot per
        logit_bias row, so a batch of 64 rows sharing one JSON schema stages
        one [2ᵖ, S_pad, V] pair, not 64.
        """
        if not self.cfg.structured_fused_decode:
            return None
        entries: list[tuple] = []  # table slot -1 -> ("g", grammar)|("b", items)
        rows: list[tuple] = []  # (seq, table slot) for constrained rows
        gram_slot: dict[int, int] = {}
        key_parts: list[tuple] = []
        smax = 1
        for s in active:
            has_g = s.structured is not None
            has_b = bool(s.logit_bias)
            if has_g and has_b:
                return None
            if has_g:
                g = s.structured.grammar
                gi = gram_slot.get(id(g))
                if gi is None:
                    gi = 1 + len(entries)
                    gram_slot[id(g)] = gi
                    entries.append(("g", g))
                    smax = max(smax, g.n_states)
                rows.append((s, gi))
                key_parts.append((s.slot, "g", id(g)))
            elif has_b:
                items = tuple(sorted(s.logit_bias.items()))
                gi = 1 + len(entries)
                entries.append(("b", items))
                rows.append((s, gi))
                key_parts.append((s.slot, "b", items))
        if not rows:
            return None  # nothing constrained: the plain program serves it
        def _pow2(n: int) -> int:
            return 1 << (n - 1).bit_length()
        G_pad, S_pad = _pow2(1 + len(entries)), _pow2(smax)
        V = self.model_cfg.vocab_size
        if G_pad * S_pad * V > self.cfg.structured_table_max_elems:
            return None
        return {"entries": entries, "rows": rows, "key": tuple(key_parts),
                "G_pad": G_pad, "S_pad": S_pad, "V": V}

    def _constrained_needs_unified(self) -> bool:
        """step() routing: True when this step's constrained rows must take
        the legacy unified degrade instead of the fused masked program."""
        active = self._decode_ready()
        if not any(s.structured is not None or s.logit_bias for s in active):
            return False  # no constrained row is decode-ready this step
        return self._plan_chain_masks(active) is None

    @_profile_phase("llmd.chain_stage")
    def _stage_chain_masks(self, active: list[Sequence]) -> Optional[dict]:
        """Stage the dense bias/transition tables + per-row automaton entry
        state for one fused masked chain. The [G_pad, S_pad, V] tables are
        LRU-cached across chains (the cache entry pins its grammar objects,
        so an id-keyed slot can never be reused by a different grammar while
        staged), leaving only the fresh [B] FSM-entry vector per chain start.
        The staging wall lands in time_mask_build — this is what replaces the
        per-STEP host mask build that stat used to count."""
        plan = self._plan_chain_masks(active)
        if plan is None:
            return None
        t0 = time.perf_counter()
        B = self.cfg.max_batch_size
        bias_dev, next_dev = self._mask_tables(plan)
        gidx = np.zeros((B,), np.int32)
        for s, gi in plan["rows"]:
            gidx[s.slot] = gi
        fsm0 = np.zeros((B,), np.int32)
        for s, _gi in plan["rows"]:
            stt = s.structured
            if stt is None:
                continue  # logit_bias row: enters (and stays) at state 0
            fresh = stt.sync(s.token_ids, s.prompt_len)
            if fresh:
                self.stats.structured_violations += fresh
                self.metrics.structured_violations.inc(fresh)
            fsm0[s.slot] = stt.state
            if not stt.mask_logged:
                stt.mask_logged = True  # first mask only: timeline, not spam
                self.flight.record(
                    s.request_id, "structured_mask", kind=stt.kind,
                    n_allowed=int(len(stt.grammar.allowed_ids(stt.state))))
        dt = time.perf_counter() - t0
        self.stats.time_mask_build += dt
        self.stats.structured_chain_stages += 1
        self.metrics.structured_mask_seconds.observe(dt)
        self.metrics.step_duration.labels(phase="chain_stage").observe(dt)
        return {"bias_tab": bias_dev, "next_tab": next_dev,
                "gidx": jnp.asarray(gidx), "fsm0": jnp.asarray(fsm0)}

    def _mask_tables(self, plan: dict) -> tuple:
        """Staged dense ``[G_pad, S_pad, V]`` bias/next tables for a mask
        plan, LRU-cached across chains AND verify steps (the key carries the
        participating constraints + pad shape; an entry pins its grammar
        objects so an id-keyed slot can never be reused by a different
        grammar while staged). Row-index vectors are NOT cached — the fused
        chain indexes by slot, the masked verify by packed row."""
        cache_key = (plan["key"], plan["G_pad"], plan["S_pad"])
        hit = self._mask_tab_cache.get(cache_key)
        if hit is not None:
            self._mask_tab_cache.move_to_end(cache_key)
            return hit[0], hit[1]
        G_pad, S_pad, V = plan["G_pad"], plan["S_pad"], plan["V"]
        bias_tab = np.zeros((G_pad, S_pad, V), np.float32)
        next_tab = np.zeros((G_pad, S_pad, V), np.int32)
        pins = []
        for gi, (kind, payload) in enumerate(plan["entries"], start=1):
            if kind == "g":
                g = payload
                pins.append(g)
                b, nx = g.dense_tables()
                S = g.n_states
                bias_tab[gi, :S] = b
                next_tab[gi, :S] = nx
            else:  # logit_bias row: state pinned at 0 (next stays 0)
                row = bias_tab[gi, 0]
                for tid, bval in payload:
                    if 0 <= tid < V:
                        # OpenAI semantics: -100 is an outright ban
                        row[tid] = (NEG_BIAS if bval <= -100.0
                                    else row[tid] + bval)
        bias_dev, next_dev = jnp.asarray(bias_tab), jnp.asarray(next_tab)
        self._mask_tab_cache[cache_key] = (bias_dev, next_dev, tuple(pins))
        while len(self._mask_tab_cache) > 8:
            self._mask_tab_cache.popitem(last=False)
        return bias_dev, next_dev

    def _spec_stage_verify_masks(self, plan) -> Optional[dict]:
        """Mask staging for one MASKED verify step: the same shared dense
        tables as the fused chain (same LRU), plus ``gidx``/``fsm0`` indexed
        by PACKED ROW (the verify plan's order — ``sids`` values), not by
        slot. ``fsm0`` is each constrained row's synced automaton state over
        its full committed history; padding rows keep gidx/fsm0 = 0 (the
        zero no-op grammar) and the program's validity mask stops them from
        touching any real row's state. Returns None when no row in the plan
        is constrained — the plain verify program serves it."""
        seqs = [s for s, _ in plan]
        if not any(s.structured is not None or s.logit_bias for s in seqs):
            return None
        mplan = self._plan_chain_masks(seqs)
        if mplan is None:
            return None  # raced: _spec_try_verify re-checks before dispatch
        t0 = time.perf_counter()
        B = self.cfg.max_batch_size
        bias_dev, next_dev = self._mask_tables(mplan)
        slot_of = {id(s): gi for s, gi in mplan["rows"]}
        gidx = np.zeros((B,), np.int32)
        fsm0 = np.zeros((B,), np.int32)
        for i, (s, _draft) in enumerate(plan):
            gi = slot_of.get(id(s))
            if gi is None:
                continue  # unconstrained row: zero no-op grammar
            gidx[i] = gi
            stt = s.structured
            if stt is not None:
                fresh = stt.sync(s.token_ids, s.prompt_len)
                if fresh:
                    self.stats.structured_violations += fresh
                    self.metrics.structured_violations.inc(fresh)
                fsm0[i] = stt.state
        dt = time.perf_counter() - t0
        self.stats.time_mask_build += dt
        self.metrics.structured_mask_seconds.observe(dt)
        return {"bias_tab": bias_dev, "next_tab": next_dev,
                "gidx": jnp.asarray(gidx), "fsm0": jnp.asarray(fsm0)}

    def _pack_buf(self) -> dict[str, np.ndarray]:
        """Rotated host-pack buffer set for the chained fast path. There are
        pipeline_depth+1 sets, indexed by dispatch count: a set is never
        refilled until the dispatch that uploaded from it has been processed
        (the readback in ``_decode_process`` forces that computation), so the
        CPU backend's zero-copy ``jnp.asarray`` aliasing can never observe a
        mutation. Full packs (chain starts) use fresh arrays instead and need
        no rotation — they are never mutated after upload."""
        if not self._pack_bufs:
            B = self.cfg.max_batch_size
            self._pack_bufs = [
                {"steps_left": np.zeros((B,), np.int32),
                 "lens": np.ones((B,), np.int32)}
                for _ in range(max(1, self.cfg.pipeline_depth) + 1)]
        return self._pack_bufs[
            self.stats.n_decode_dispatches % len(self._pack_bufs)]

    @_profile_phase("llmd.decode_dispatch")
    def _decode_dispatch(self, active: list[Sequence], k: int, chain: Optional[dict],
                         wall_start: float, off: int = 0) -> dict:
        """Pack host state (+ the un-processed offset across ALL in-flight calls)
        and launch one fused k-step decode chained on ``chain``'s device-resident
        outputs. Returns the in-flight record; results are NOT read.

        Two pack regimes (PERF.md Lever 12):

        * chain start (or ``pack_overlap`` off): full host pack into fresh
          arrays — the admission/retire boundary where the host owns the loop.
        * chained fast path: the previous call's device-resident tokens,
          positions, kv lens, and FSM states feed straight back in; the host
          re-derives only ``steps_left`` (the per-row hard budget) and, when a
          row grew a page, the page tables. One small upload instead of nine,
          and the pack wall is overlapped with the in-flight device chain
          (accounted as time_pack_overlap, not time_host_pack).
        """
        B = self.cfg.max_batch_size
        fast = chain is not None and self.cfg.pack_overlap
        if fast:
            with jax.profiler.TraceAnnotation("llmd.pack_overlap"):
                bufs = self._pack_buf()
                steps_left, lens_np = bufs["steps_left"], bufs["lens"]
                steps_left.fill(0)
                sig = chain["pages_sig"]
                pages_changed = False
                for j, s in enumerate(active):
                    i = s.slot
                    eff_len = len(s.token_ids) + off  # host view + in-flight
                    lens_np[i] = eff_len  # probe-only on this path (no upload)
                    gen = eff_len - s.prompt_len
                    steps_left[i] = max(0, min(s.max_tokens - gen,
                                               self.cfg.max_model_len - eff_len,
                                               k))
                    if len(s.pages) != sig[j]:
                        pages_changed = True
                if pages_changed:
                    pts_np = np.full((B, self.cfg.max_pages_per_seq), -1,
                                     np.int32)
                    for s in active:
                        pts_np[s.slot, : len(s.pages)] = s.pages
                    pts_dev = jnp.asarray(pts_np)
                    pages_sig = tuple(len(s.pages) for s in active)
                else:
                    pts_np, pts_dev, pages_sig = (chain["pts_np"],
                                                  chain["pts_dev"], sig)
                toks_in, pos_in, lens_in = (chain["last_toks"],
                                            chain["pos_out"],
                                            chain["lens_out"])
                temp_dev, tk_dev, tp_dev, lora_dev = (
                    chain["temp_dev"], chain["tk_dev"], chain["tp_dev"],
                    chain["lora_dev"])
                steps_dev = jnp.asarray(steps_left)
                mask = chain["mask"]
                fsm_in = chain["fsm_out"]
        else:
            pos = np.full((B,), -1, np.int32)
            pts_np = np.full((B, self.cfg.max_pages_per_seq), -1, np.int32)
            lens_np = np.ones((B,), np.int32)
            lora_idx = np.zeros((B,), np.int32)
            steps_left = np.zeros((B,), np.int32)
            temp = np.zeros((B,), np.float32)
            tk = np.zeros((B,), np.int32)
            tp = np.ones((B,), np.float32)
            toks = np.zeros((B,), np.int32)
            for s in active:
                i = s.slot
                eff_len = len(s.token_ids) + off  # host view + in-flight tokens
                toks[i] = s.token_ids[-1]  # unused when chaining (device wins)
                pos[i] = eff_len - 1
                pts_np[i, : len(s.pages)] = s.pages
                lens_np[i] = eff_len
                lora_idx[i] = self._lora_slot(s)
                sp: SamplingParams = s.sampling
                temp[i], tk[i], tp[i] = sp.temperature, sp.top_k, sp.top_p
                gen = eff_len - s.prompt_len
                steps_left[i] = max(0, min(s.max_tokens - gen,
                                           self.cfg.max_model_len - eff_len, k))
            pages_sig = tuple(len(s.pages) for s in active)
            pts_dev = jnp.asarray(pts_np)
            pos_in, lens_in = jnp.asarray(pos), jnp.asarray(lens_np)
            temp_dev, tk_dev, tp_dev = (jnp.asarray(temp), jnp.asarray(tk),
                                        jnp.asarray(tp))
            lora_dev = jnp.asarray(lora_idx)
            steps_dev = jnp.asarray(steps_left)
            toks_in = (chain["last_toks"] if chain is not None
                       else jnp.asarray(toks))
            if chain is not None:
                mask, fsm_in = chain["mask"], chain["fsm_out"]
            else:
                mask = (self._stage_chain_masks(active)
                        if any(s.structured is not None or s.logit_bias
                               for s in active) else None)
                fsm_in = mask["fsm0"] if mask is not None else None
                for s in active:
                    self.flight.record(s.request_id, "chain_dispatch", k=k,
                                       masked=mask is not None)
        self._key, sub = jax.random.split(self._key)
        t1 = time.perf_counter()
        if fast:
            # the device is still executing chain N while this pack ran: its
            # wall is hidden, not serialized — keep time_host_pack honest
            self.stats.time_pack_overlap += t1 - wall_start
            self.metrics.step_duration.labels(phase="pack_overlap").observe(
                t1 - wall_start)
        else:
            self.stats.time_host_pack += t1 - wall_start
            self.metrics.step_duration.labels(phase="pack").observe(
                t1 - wall_start)
        if mask is not None:
            (toks_out, last_toks, pos_out, lens_out, fsm_out, self.cache,
             cnt, moe_drop) = self._decode_multi_masked_fn(
                self._run_params(), self.cache, toks_in, pos_in, pts_dev,
                lens_in, temp_dev, tk_dev, tp_dev, sub, steps_dev, lora_dev,
                fsm_in, mask["gidx"], mask["bias_tab"], mask["next_tab"],
            )
        else:
            (toks_out, last_toks, pos_out, lens_out, self.cache, cnt,
             moe_drop) = (
                self._decode_multi_fn(
                    self._run_params(), self.cache, toks_in, pos_in, pts_dev,
                    lens_in, temp_dev, tk_dev, tp_dev, sub, steps_dev,
                    lora_dev,
                ))
            fsm_out = None
        self.stats.time_decode_steps += time.perf_counter() - wall_start
        self.stats.n_decode_dispatches += 1
        prog = "decode" if mask is None else "decode_masked"
        self.programs.record_dispatch(prog)
        if chain is not None:
            self.stats.n_chained_dispatches += 1
        self.metrics.step_duration.labels(phase="decode_dispatch").observe(
            time.perf_counter() - wall_start,
            exemplar=self._trace_exemplar(active))
        # first probe at dispatch _attn_probe_every, not 1: serving engines
        # reach it in seconds, while short-lived engines (tests, tiny bench)
        # never pay the probe's one-off compile
        if (self._attn_probe_fn is not None
                and self.stats.n_decode_dispatches % self._attn_probe_every == 0):
            self._observe_attn_phase(pts_np, lens_np, k)
        if (self._moe_probe_fns is not None
                and self.stats.n_decode_dispatches % self._attn_probe_every == 0):
            self._observe_moe_phase(k)
        # Start the device->host copy of everything _decode_process will read.
        # Remote/tunneled runtimes defer execution until a result is demanded;
        # the async-copy hint makes the call run (and its tokens land on the
        # host) while the host loop does other work, so the later np.asarray
        # is a near-free read instead of RTT + compute.
        host_reads = [toks_out]
        if self._eplb is not None:
            host_reads.append(cnt)
        if self.model_cfg.is_moe:
            host_reads.append(moe_drop)
        for arr in host_reads:
            try:
                arr.copy_to_host_async()
            except (AttributeError, RuntimeError):
                break
        # analytic cost of this call, from its packed shape: the scan runs k
        # steps over all B slots (masked rows compute too), each step streams
        # the weights once and each active row reads its resident KV per step.
        # Stashed on the rec; _decode_process joins it with the measured wall
        # and the kept-token count when the readback lands.
        util_cost = None
        if self.util is not None:
            util_cost = self.util.cost(
                prog, slot_tokens=B * k, weight_passes=k,
                kv_read_tokens=k * int(sum(int(lens_np[s.slot])
                                           for s in active)),
                kv_write_tokens=int(steps_left.sum()))
        return {
            "util_cost": util_cost,
            "rows": [(s, s.slot) for s in active], "prog": prog,
            "toks_out": toks_out, "last_toks": last_toks, "cnt": cnt, "k": k,
            "moe_drop": moe_drop,
            # device-resident chain point for the next pipelined dispatch
            "pos_out": pos_out, "lens_out": lens_out, "fsm_out": fsm_out,
            "mask": mask, "pts_np": pts_np, "pts_dev": pts_dev,
            "pages_sig": pages_sig, "temp_dev": temp_dev, "tk_dev": tk_dev,
            "tp_dev": tp_dev, "lora_dev": lora_dev,
        }

    def _observe_attn_phase(self, pts: np.ndarray, lens: np.ndarray, k: int) -> None:
        """Sampled attention-share probe: time one attention-only jitted call at
        the shapes the dispatch just ran, observe wall x layers x k as the
        estimated attention share of a fused decode call. The first invocation
        compiles and is discarded (a compile sample would dominate the
        histogram); a probe failure disables further probes rather than
        degrading serving — the step itself already ran."""
        try:
            args = (self.cache, jnp.asarray(pts), jnp.asarray(lens))
            if not self._attn_probe_warm:
                self._attn_probe_fn(*args).block_until_ready()
                self._attn_probe_warm = True
            t0 = time.perf_counter()
            self._attn_probe_fn(*args).block_until_ready()
            dt = time.perf_counter() - t0
            self.metrics.step_duration.labels(phase="attn").observe(
                dt * self.model_cfg.num_layers * k)
        except Exception:  # noqa: BLE001 — observability must not take down serving
            self._attn_probe_fn = None

    def _observe_moe_phase(self, k: int) -> None:
        """Sampled MoE stage probe (sorted dispatch only): time the jitted
        dispatch / experts / combine stage calls at the fused-decode token
        shape against the live expert bank, observe each wall x layers x k
        into its step_duration phase. Synthetic uniform routing — the probe
        measures the stage mechanics (sort/scatter, grouped GEMM, inverse
        permute), not this step's skew; EPLB load stats come from the real
        counts. First call compiles and is discarded; failure disables the
        probe, never serving."""
        try:
            p = self._run_params()
            if "moe_wi_q" in p:
                wi, wo = p["moe_wi_q"][0], p["moe_wo_q"][0]
                wi_s, wo_s = p["moe_wi_scale"][0], p["moe_wo_scale"][0]
            else:
                wi, wo = p["moe_wi"][0], p["moe_wo"][0]
                wi_s = wo_s = None
            cfg = self.model_cfg
            B = self.cfg.max_batch_size
            kk = cfg.moe_top_k
            S = wi.shape[0]
            x = jnp.zeros((B, cfg.hidden_size), cfg.jax_dtype)
            idx = (jnp.arange(B * kk, dtype=jnp.int32) % S).reshape(B, kk)
            topw = jnp.full((B, kk), 1.0 / kk, cfg.jax_dtype)
            valid = jnp.ones((B, 1), jnp.int32)
            fd, fe, fc = self._moe_probe_fns
            if not self._moe_probe_warm:
                staged = fd(x, idx, topw, valid)
                ye = fe(staged[0], staged[4], staged[5], wi, wo, wi_s, wo_s)
                fc(ye, staged[1], staged[2], staged[3]).block_until_ready()
                self._moe_probe_warm = True
            scale = cfg.num_layers * k
            with jax.profiler.TraceAnnotation("llmd.moe_dispatch_probe"):
                t0 = time.perf_counter()
                staged = fd(x, idx, topw, valid)
                jax.block_until_ready(staged)
                self.metrics.step_duration.labels(phase="moe_dispatch").observe(
                    (time.perf_counter() - t0) * scale)
            xs, row, tok, wf, block_slot, block_rows = staged
            with jax.profiler.TraceAnnotation("llmd.moe_experts_probe"):
                t0 = time.perf_counter()
                ye = fe(xs, block_slot, block_rows, wi, wo, wi_s, wo_s)
                ye.block_until_ready()
                self.metrics.step_duration.labels(phase="moe_experts").observe(
                    (time.perf_counter() - t0) * scale)
            with jax.profiler.TraceAnnotation("llmd.moe_combine_probe"):
                t0 = time.perf_counter()
                fc(ye, row, tok, wf).block_until_ready()
                self.metrics.step_duration.labels(phase="moe_combine").observe(
                    (time.perf_counter() - t0) * scale)
        except Exception:  # noqa: BLE001 — observability must not take down serving
            self._moe_probe_fns = None

    @_profile_phase("llmd.decode_process")
    def _decode_process(self, rec: dict) -> None:
        """Read one in-flight decode call's results and apply them to host state."""
        t1 = time.perf_counter()
        t1_ns = time.time_ns()
        n_tokens = 0
        if self._eplb is not None:
            self._eplb_record(rec["cnt"])
        # llmd-lint: allow[hot-host-sync] designed sync point: the one deferred readback per decode step (dispatch/process split hides it behind the next dispatch)
        toks_out = np.asarray(rec["toks_out"])  # [k, B] (device sync point)
        if self.model_cfg.is_moe:
            # the async copy was started at dispatch; toks_out above already
            # paid this step's sync, so the drop scalar read is free
            self._moe_record_dropped(rec["moe_drop"])
        t2 = time.perf_counter()
        now = time.monotonic()
        for s, slot in rec["rows"]:
            if s.finished or s.slot != slot or self.running[slot] is not s:
                continue  # aborted / preempted / replaced while in flight
            new = [int(t) for t in toks_out[:, slot]]
            kept: list[int] = []
            finished, reason = False, None
            for t in new:
                kept.append(t)
                s.token_ids.append(t)
                finished, reason = self._check_finish(s, t)
                if finished:
                    break
            # the newest token's KV is never written yet → computed = len - 1
            s.num_computed = len(s.token_ids) - 1
            if s.structured is not None:
                # replay the landed tokens through the host automaton: keeps
                # the cursor current for the next chain staging and counts
                # violations (device-masked sampling should make fresh == 0)
                fresh = s.structured.sync(s.token_ids, s.prompt_len)
                if fresh:
                    self.stats.structured_violations += fresh
                    self.metrics.structured_violations.inc(fresh)
            if s.first_token_time is None:
                s.first_token_time = now
                self.flight.record(
                    s.request_id, "first_token",
                    ttft_ms=round((now - s.arrival_time) * 1e3, 3))
            s.maybe_commit_blocks(self.allocs[s.rank])
            self.stats.total_decode_tokens += len(kept)
            self.stats.decode_tokens_fused += len(kept)
            if kept:
                if not s.spec_armed:
                    s.spec_flips += 1
                s.spec_armed = True  # fresh tokens landed: re-probe this row
            n_tokens += len(kept)
            # one progress event per fused k-step call (per-N decode progress)
            self.flight.record(s.request_id, "decode", n_tokens=len(kept),
                               generated=s.num_generated)
            if finished:
                self._retire(s, reason)
            self._outputs.append(EngineOutput(
                request_id=s.request_id, new_token_ids=kept, finished=finished,
                finish_reason=reason, num_cached_prompt_tokens=s.num_cached_prompt,
                prompt_len=s.prompt_len,
            ))
        t3 = time.perf_counter()
        st = self.stats
        st.time_device += t2 - t1
        st.time_device_decode += t2 - t1
        st.time_postprocess += t3 - t2
        st.time_decode_steps += t3 - t1
        st.n_decode_calls += 1
        self.programs.record_complete(rec["prog"])
        if n_tokens:
            self.metrics.decode_tokens.inc(n_tokens)
        self.metrics.step_duration.labels(phase="decode_process").observe(
            t3 - t1, exemplar=self._trace_exemplar([s for s, _ in rec["rows"]]))
        if self.util is not None and rec.get("util_cost") is not None:
            # kept tokens commit; everything else the B x k scan computed
            # (masked slots, post-EOS steps, rows preempted in flight) is the
            # padding residual
            self.util.record(
                rec["prog"], rec["util_cost"], t3 - t1, committed=n_tokens,
                compile_counts=self.programs.compile_counts())
        self._emit_step_spans("decode", [s for s, _ in rec["rows"]], t1_ns,
                              len(rec["rows"]), n_tokens)

    def _retire(self, seq: Sequence, reason: Optional[str]) -> None:
        """Shared retirement path: free slot + pages, drop from the live map."""
        seq.finished = True
        seq.finish_reason = reason
        if seq.structured is not None:
            # final automaton sync: a constrained generation that ends before
            # the grammar accepts (max_tokens/max_model_len truncation) is a
            # violation from the client's point of view — the text won't parse
            fresh = seq.structured.sync(seq.token_ids, seq.prompt_len)
            n_bad = fresh + (0 if seq.structured.complete else 1)
            if n_bad:
                self.stats.structured_violations += n_bad
                self.metrics.structured_violations.inc(n_bad)
        if seq.spec_drafted > 0:
            constrained = seq.structured is not None or bool(seq.logit_bias)
            self.metrics.spec_acceptance.labels(
                constrained="yes" if constrained else "no").observe(
                seq.spec_accepted / seq.spec_drafted)
        # decision-ledger attrs ride the terminal event (None-valued attrs
        # are dropped by the recorder, so untouched levers add nothing)
        decision_attrs = {}
        if self._decisions_on:
            decision_attrs = dict(
                spec_drafted=seq.spec_drafted or None,
                spec_accepted=(seq.spec_accepted
                               if seq.spec_drafted else None),
                spec_flips=seq.spec_flips or None,
                cached_tokens=seq.num_cached_prompt or None)
        self.flight.finish(
            seq.request_id, event="retired", reason=reason or "",
            generated=seq.num_generated,
            ttft_ms=round((seq.first_token_time - seq.arrival_time) * 1e3, 3)
            if seq.first_token_time is not None else None,
            **decision_attrs)
        if self.kv_connector is not None and seq.block_hashes:
            # K5 save path: dispatch the chunked staging here (cheap, same
            # helper as the P/D export path), drain + hand bytes to the
            # external engine on the connector thread off the locked step loop.
            try:
                from llmd_tpu.disagg.transfer import drain_staged, stage_pages

                n = len(seq.block_hashes)
                ps = self.cfg.page_size
                parts = stage_pages(self.cache, seq.pages[:n], self.cfg.num_pages,
                                    self.cfg.offload_staging_blocks)
                hashes = list(seq.block_hashes)
                chunks = [seq.token_ids[i * ps : (i + 1) * ps] for i in range(n)]
                rid = seq.request_id

                def _drain(parts=parts, hashes=hashes, chunks=chunks, rid=rid):
                    try:
                        self.kv_connector.save_blocks(hashes, chunks,
                                                      drain_staged(parts))
                    except Exception:
                        pass  # external engine down: never fails serving
                    try:
                        self.kv_connector.request_finished(rid)
                    except Exception:
                        pass

                self._connector_pool.submit(_drain)
            except Exception:
                pass  # dispatch failure must not fail retirement either
        if seq.admit_features is not None and seq.first_token_time is not None:
            # one predictor training row per completed request (engine-emitted
            # traces, not a synthetic generator — latency-predictor.md:58)
            now = time.monotonic()
            n_gen = max(1, seq.num_generated)
            self.latency_trace.append(dict(
                seq.admit_features,
                tokens_generated=float(n_gen),
                ttft_ms=(seq.first_token_time - seq.arrival_time) * 1e3,
                tpot_ms=((now - seq.first_token_time) / max(1, n_gen - 1)) * 1e3
                if n_gen > 1 else None,
            ))
        if seq.slot >= 0:
            self.running[seq.slot] = None
            seq.slot = -1
            if self.lora_registry is not None:
                self.lora_registry.on_finished(seq.lora_id)
        self._free_seq(seq)
        self.seqs.pop(seq.request_id, None)

    @_profile_phase("llmd.mask_build")
    def _build_bias(self, rows_and_seqs: list[tuple[int, "Sequence"]],
                    logits_shape: tuple) -> Optional[np.ndarray]:
        """Host-side additive ``[B, V]`` bias for a sample batch: the grammar
        allow-mask of each constrained row's current automaton state, plus any
        OpenAI ``logit_bias`` entries. Returns None when the batch carries no
        constrained row — the common case keeps the exact unbiased sampler
        program (no bias upload, no second compile)."""
        if not any(s.structured is not None or s.logit_bias
                   for _, s in rows_and_seqs):
            return None
        t0 = time.perf_counter()
        B, V = logits_shape[0], logits_shape[-1]
        bias = np.zeros((B, V), np.float32)
        for i, s in rows_and_seqs:
            st = s.structured
            if st is not None:
                fresh = st.sync(s.token_ids, s.prompt_len)
                if fresh:
                    self.stats.structured_violations += fresh
                    self.metrics.structured_violations.inc(fresh)
                st.grammar.fill_bias(bias[i], st.state)
                self.stats.structured_mask_builds += 1
                if not st.mask_logged:
                    st.mask_logged = True  # first mask only: timeline, not spam
                    self.flight.record(
                        s.request_id, "structured_mask", kind=st.kind,
                        n_allowed=int(len(st.grammar.allowed_ids(st.state))))
            if s.logit_bias:
                row = bias[i]
                for tid, b in s.logit_bias.items():
                    if 0 <= tid < V:
                        # OpenAI semantics: -100 is an outright ban
                        row[tid] = NEG_BIAS if b <= -100.0 else row[tid] + b
        dt = time.perf_counter() - t0
        self.stats.time_mask_build += dt
        self.metrics.structured_mask_seconds.observe(dt)
        return bias

    def _sample_dispatch(self, rows_and_seqs: list[tuple[int, "Sequence"]],
                         logits: jax.Array,
                         bias: Optional[np.ndarray] = None) -> dict:
        """Launch sampling on device (chains on the step that made ``logits``)
        and start the device->host copy; no sync point here."""
        B = logits.shape[0]
        temp = np.zeros((B,), np.float32)
        tk = np.zeros((B,), np.int32)
        tp = np.ones((B,), np.float32)
        for i, s in rows_and_seqs:
            sp: SamplingParams = s.sampling
            temp[i] = sp.temperature
            tk[i] = sp.top_k
            tp[i] = sp.top_p
        self._key, sub = jax.random.split(self._key)
        if bias is not None:
            # biased program: grammar masks / logit_bias add ON DEVICE before
            # argmax — logits never leave the accelerator. Lazily jitted, so
            # engines that never see a constrained request never compile it.
            sampled = sample_tokens_biased(
                logits.astype(jnp.float32), jnp.asarray(bias), sub,
                jnp.asarray(temp), jnp.asarray(tk), jnp.asarray(tp))
        else:
            sampled = sample_tokens(logits.astype(jnp.float32), sub,
                                    jnp.asarray(temp), jnp.asarray(tk), jnp.asarray(tp))
        try:
            sampled.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        self.programs.record_dispatch("sample")
        return {"sampled": sampled,
                "rows": [(i, s, s.slot) for i, s in rows_and_seqs]}

    def _flush_pending_sample(self) -> None:
        rec, self._pending_sample = self._pending_sample, None
        if rec is not None:
            self._sample_apply(rec)

    def _sample_apply(self, rec: dict) -> None:
        """Read one dispatched sample's tokens (device sync point) and apply."""
        # llmd-lint: allow[hot-host-sync] designed sync point: deferred sample readback, overlapped with the next dispatch
        sampled = np.asarray(rec["sampled"])
        self.programs.record_complete("sample")
        now = time.monotonic()
        for i, s, slot in rec["rows"]:
            if s.finished or s.slot != slot or self.running[slot] is not s:
                continue  # aborted / preempted while the sample was in flight
            tok = int(sampled[i])
            s.token_ids.append(tok)
            if not s.spec_armed:
                s.spec_flips += 1
            s.spec_armed = True  # fresh token landed: re-probe this row's drafter
            if s.structured is not None:
                fresh = s.structured.sync(s.token_ids, s.prompt_len)
                if fresh:  # masked sampling should make this unreachable
                    self.stats.structured_violations += fresh
                    self.metrics.structured_violations.inc(fresh)
            if s.first_token_time is None:
                s.first_token_time = now
                self.flight.record(
                    s.request_id, "first_token",
                    ttft_ms=round((now - s.arrival_time) * 1e3, 3))
            finished, reason = self._check_finish(s, tok)
            if finished:
                self._retire(s, reason)
            self._outputs.append(EngineOutput(
                request_id=s.request_id, new_token_ids=[tok], finished=finished,
                finish_reason=reason, num_cached_prompt_tokens=s.num_cached_prompt,
                prompt_len=s.prompt_len,
            ))

    def _check_finish(self, seq: Sequence, tok: int) -> tuple[bool, Optional[str]]:
        sp: SamplingParams = seq.sampling
        if not sp.ignore_eos and tok in (sp.stop_token_ids or ()):
            return True, "stop"
        if seq.num_generated >= seq.max_tokens:
            return True, "length"
        if len(seq.token_ids) >= self.cfg.max_model_len:
            return True, "length"
        return False, None

    # ------------------------------------------------------------- embeddings
    def embed(self, token_ids: list[int], lora_id: Optional[str] = None,
              rank: int = 0) -> list[float]:
        """Mean-pooled, L2-normalised final hidden state (/v1/embeddings path).

        Runs chunk-wise through the compiled embed program (flat single-sequence
        batches), borrowing KV pages from the requesting rank's partition only
        for the duration of the call. The caller serialises against the step
        loop (run_locked in the server).
        """
        if not token_ids:
            raise ValueError("empty input")
        token_ids = token_ids[: self.cfg.max_model_len - 1]
        chunk = self.cfg.prefill_chunk
        ps = self.cfg.page_size
        need = (len(token_ids) + ps - 1) // ps
        alloc = self.allocs[rank if 0 <= rank < self.num_ranks else 0]
        pages: list[int] = []
        for _ in range(need):
            pid = alloc.allocate()
            if pid is None:
                for p in pages:
                    alloc.release(p)
                raise RuntimeError("no free KV pages for embedding request")
            pages.append(pid)
        try:
            pt = np.full((1, self.cfg.max_pages_per_seq), -1, np.int32)
            pt[0, : len(pages)] = pages
            lora_idx = np.full(
                (chunk,),
                self.lora_registry.slot_of(lora_id) if self.lora_registry else 0,
                np.int32)
            acc = np.zeros((self.model_cfg.hidden_size,), np.float64)
            for start in range(0, len(token_ids), chunk):
                n = min(chunk, len(token_ids) - start)
                toks = np.zeros((chunk,), np.int32)
                toks[:n] = token_ids[start : start + n]
                pos = np.full((chunk,), -1, np.int32)
                pos[:n] = np.arange(start, start + n)
                h_sum, self.cache = self._embed_fn(
                    self._run_params(), self.cache, jnp.asarray(toks),
                    jnp.asarray(pos), jnp.asarray(pt),
                    jnp.asarray([start + n], jnp.int32),
                    jnp.asarray([0, n], jnp.int32), jnp.asarray(lora_idx),
                )
                acc += np.asarray(h_sum, np.float64)
        finally:
            for p in pages:
                alloc.release(p)
        vec = acc / max(1, len(token_ids))
        norm = float(np.linalg.norm(vec))
        return (vec / norm if norm > 0 else vec).astype(float).tolist()

    # ------------------------------------------------------------- convenience
    def generate(self, prompts: list[list[int]], sampling: Optional[SamplingParams] = None) -> dict[str, list[int]]:
        """Blocking batch generation (tests/bench); returns request_id → generated ids."""
        for i, p in enumerate(prompts):
            self.add_request(f"req-{i}", p, sampling)
        done: dict[str, list[int]] = {f"req-{i}": [] for i in range(len(prompts))}
        while self.has_work():
            for out in self.step():
                done[out.request_id].extend(out.new_token_ids)
        # quiesce invariant: every launched fused call was processed — a gap
        # means a chained in-flight record was orphaned and its sampled
        # tokens silently dropped (engine.py n_decode_dispatches docstring)
        assert (self.stats.n_decode_dispatches == self.stats.n_decode_calls
                and not self._pending_decode), (
            f"decode pipeline leak at quiesce: dispatched="
            f"{self.stats.n_decode_dispatches} "
            f"processed={self.stats.n_decode_calls} "
            f"pending={len(self._pending_decode)}")
        # generalized form (programs.py): the per-program ledger must balance
        # for EVERY registry entry at every drain, not just the decode pair
        assert self.programs.quiesced(), (
            f"program ledger leak at quiesce: {self.programs.counters()}")
        return done
