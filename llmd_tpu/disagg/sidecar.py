"""Routing sidecar: per-decode-pod proxy orchestrating the P→D multi-step flow.

Parity: reference docs/architecture/advanced/disaggregation/README.md:104-131 and the
deployment shape in recipes/modelserver/base/single-host/pd/vllm/patch-sidecar.yaml —
the sidecar listens on the pod's serving port in front of the local decode engine,
reads the router's ``x-prefiller-host-port`` header, and:

1. sends the request to the prefiller with ``max_tokens=1`` + kv_transfer_params
   ``{do_remote_decode: true}`` (sampling disabled unless ``enable_prefiller_sampling``),
2. captures the returned transfer handle from the prefill response,
3. injects it (``do_remote_prefill``) into the original request and forwards it to the
   local decode engine, streaming the response straight through,
4. falls back to decoder-only (aggregated) when the prefiller fails with 5xx or is
   unreachable (README.md:130).

E/PD and E/P/D (encode disaggregation, guides/multimodal-serving/e-disaggregation/
README.md): with ``encode_hosts`` configured, requests carrying media content
parts first fan those parts out across the encode workers — one worker per item,
concurrently, round-robin — and attach the returned embedding rows as
``mm_items`` before the normal (P→)D flow. Text-only requests skip the E stage
entirely, exactly as the reference specifies.
"""

from __future__ import annotations

import asyncio
import copy
import json
from typing import Optional

import aiohttp
from aiohttp import web

from llmd_tpu.core.request import HDR_PREFILLER_HOST_PORT

GEN_PATHS = ("/v1/completions", "/v1/chat/completions")


class RoutingSidecar:
    def __init__(
        self,
        decode_addr: str,
        host: str = "127.0.0.1",
        port: int = 0,
        enable_prefiller_sampling: bool = False,
        prefill_timeout_s: float = 120.0,
        encode_hosts: Optional[list[str]] = None,
        encode_timeout_s: float = 60.0,
    ) -> None:
        self.decode_addr = decode_addr
        self.host, self.port = host, port
        self.enable_prefiller_sampling = enable_prefiller_sampling
        self.prefill_timeout = aiohttp.ClientTimeout(total=prefill_timeout_s)
        self.encode_hosts = list(encode_hosts or [])
        self.encode_timeout = aiohttp.ClientTimeout(total=encode_timeout_s)
        self._encode_rr = 0  # round-robin cursor over the encode pool
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self.stats = {"pd_requests": 0, "aggregated_requests": 0,
                      "prefill_fallbacks": 0, "encoded_items": 0,
                      "encode_failures": 0}

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        app = web.Application(client_max_size=32 * 1024 * 1024)
        for path in GEN_PATHS:
            app.router.add_post(path, self._generate)
        app.router.add_route("*", "/{tail:.*}", self._passthrough)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._session:
            await self._session.close()
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------------
    async def _generate(self, request: web.Request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)

        if self.encode_hosts:
            body = await self._run_encode(body)

        prefiller = request.headers.get(HDR_PREFILLER_HOST_PORT)
        if prefiller:
            ktp = await self._run_prefill(request.path, body, prefiller)
            if ktp is not None:
                body = dict(body)
                body["kv_transfer_params"] = {"do_remote_prefill": True, **ktp}
                self.stats["pd_requests"] += 1
            else:
                self.stats["prefill_fallbacks"] += 1
        else:
            self.stats["aggregated_requests"] += 1
        return await self._forward_decode(request, body)

    async def _run_encode(self, body: dict) -> dict:
        """E stage: fan media parts out across the encode pool, one item per
        worker concurrently (the reference's parallelized-across-entries
        property); attach the rows as mm_items. Failures leave the request
        un-annotated — the engine then serves text-only placeholders rather
        than 500ing the whole request (encode is best-effort like prefill)."""
        from llmd_tpu.disagg.encode import iter_media_parts

        parts = list(iter_media_parts(body))
        if not parts or body.get("mm_items"):
            return body  # text-only, or already encoded upstream

        async def one(part: dict) -> Optional[dict]:
            # try up to two distinct workers (round-robin) before giving up on
            # an item; a failed item costs only ITS OWN encode downstream — the
            # successes still attach (partial results beat discarding work)
            for _ in range(min(2, len(self.encode_hosts))):
                host = self.encode_hosts[self._encode_rr % len(self.encode_hosts)]
                self._encode_rr += 1
                try:
                    async with self._session.post(
                        f"http://{host}/v1/encode", json={"items": [part]},
                        timeout=self.encode_timeout,
                    ) as resp:
                        if resp.status != 200:
                            continue
                        return (await resp.json())["items"][0]
                except (aiohttp.ClientError, asyncio.TimeoutError, KeyError, IndexError):
                    continue
            return None

        items = await asyncio.gather(*(one(p) for p in parts))
        ok = [i for i in items if i is not None]
        if len(ok) < len(items):
            self.stats["encode_failures"] += len(items) - len(ok)
        self.stats["encoded_items"] += len(ok)
        if ok:
            body = dict(body)
            body["mm_items"] = ok  # engine matches by part hash; missing items
            # re-encode there (tower) or degrade the request to text-only
        return body

    async def _run_prefill(self, path: str, body: dict, prefiller: str) -> Optional[dict]:
        """Phase 1: remote prefill. Returns the transfer handle, or None → fallback."""
        pbody = copy.deepcopy(body)
        pbody["max_tokens"] = 1
        pbody["stream"] = False
        pbody["kv_transfer_params"] = {"do_remote_decode": True}
        if not self.enable_prefiller_sampling:
            pbody["temperature"] = 0.0
        try:
            async with self._session.post(
                f"http://{prefiller}{path}", json=pbody, timeout=self.prefill_timeout
            ) as resp:
                if resp.status >= 500:
                    return None
                data = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, json.JSONDecodeError, OSError):
            return None
        ktp = data.get("kv_transfer_params")
        if not ktp or not ktp.get("remote_request_id"):
            return None
        if not ktp.get("remote_host"):
            ktp["remote_host"] = prefiller.rsplit(":", 1)[0]
        return ktp

    async def _forward_decode(self, request: web.Request, body: dict):
        """Phase 2: forward to the local decode engine, streaming straight through."""
        try:
            async with self._session.post(
                f"http://{self.decode_addr}{request.path}", json=body,
                timeout=aiohttp.ClientTimeout(total=None),
            ) as upstream:
                if not body.get("stream"):
                    payload = await upstream.read()
                    return web.Response(
                        body=payload, status=upstream.status,
                        content_type=upstream.content_type,
                    )
                resp = web.StreamResponse(status=upstream.status, headers={
                    "Content-Type": upstream.headers.get("Content-Type", "text/event-stream"),
                    "Cache-Control": "no-cache",
                })
                await resp.prepare(request)
                async for chunk in upstream.content.iter_any():
                    await resp.write(chunk)
                await resp.write_eof()
                return resp
        except (aiohttp.ClientError, OSError) as e:
            return web.json_response(
                {"error": {"message": f"decode engine unreachable: {e}"}}, status=502
            )

    async def _passthrough(self, request: web.Request):
        """Non-generate traffic (health, models, metrics) proxied to the engine."""
        try:
            data = await request.read()
            async with self._session.request(
                request.method, f"http://{self.decode_addr}{request.path_qs}",
                data=data or None,
                headers={k: v for k, v in request.headers.items()
                         if k.lower() not in ("host", "content-length")},
            ) as upstream:
                payload = await upstream.read()
                return web.Response(
                    body=payload, status=upstream.status,
                    content_type=upstream.content_type,
                )
        except (aiohttp.ClientError, OSError) as e:
            return web.json_response(
                {"error": {"message": f"decode engine unreachable: {e}"}}, status=502
            )


def main() -> None:
    """CLI: python -m llmd_tpu.disagg.sidecar --port 8000 --engine 127.0.0.1:8200

    Deployment entrypoint (reference patch-sidecar.yaml: sidecar on the pod's
    serving port, engine on the local port behind it)."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--engine", default="127.0.0.1:8200",
                    help="local decode engine address")
    ap.add_argument("--enable-prefiller-sampling", action="store_true")
    ap.add_argument("--prefill-timeout", type=float, default=120.0)
    ap.add_argument("--encode-hosts", default="",
                    help="comma-separated encode-worker host:port pool (E/PD); "
                         "empty disables the encode stage")
    ap.add_argument("--encode-timeout", type=float, default=60.0)
    args = ap.parse_args()

    sidecar = RoutingSidecar(
        args.engine, host=args.host, port=args.port,
        enable_prefiller_sampling=args.enable_prefiller_sampling,
        prefill_timeout_s=args.prefill_timeout,
        encode_hosts=[h for h in args.encode_hosts.split(",") if h],
        encode_timeout_s=args.encode_timeout,
    )

    async def run() -> None:
        await sidecar.start()
        print(f"llmd-tpu routing sidecar on http://{sidecar.address} "
              f"-> engine {args.engine}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
