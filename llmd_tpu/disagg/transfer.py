"""KV-transfer engine: pull-model block movement between prefill and decode pods.

Plays NIXL's role on the reference GPU path (disaggregation/README.md:133-178) the way
the reference's own TPU connector does it — host-memory-assisted (`TPUConnectorHMA`,
guides/pd-disaggregation/modelserver/tpu/base/vllm/patch-prefill.yaml:17-27: KV port
9100, side channel 9600) — because XLA owns HBM and one-sided device reads into live
buffers are not expressible; instead:

- **prefill (producer)**: after prefill completes, the request's complete KV blocks are
  gathered device→host into ONE contiguous staging buffer (the contiguous-layout trick
  the reference's offloader uses for 4-5× transfer throughput, kv-offloader.md:33-40)
  and registered under the request id,
- **decode (consumer)**: pulls blocks over a TCP side channel (pull model ≙ NIXL's
  one-sided read: decode fetches when ready, prefill stays passive), verifies the
  chained block hashes, writes host→device, and commits the blocks into its local
  prefix cache — so admission reuses them exactly like local prefix hits, and any
  failure (connection refused, hash mismatch, pool pressure) degrades to recompute
  (`kv_load_failure_policy=recompute`, operations-vllm.md:84-100),
- **release**: decode's post-injection notify frees producer-side blocks (the NIXL
  notify semantics, operations-vllm.md:48-60); a TTL reaper frees abandoned exports
  (decode died mid-transfer).

The framed wire protocol is implementation-neutral; the C++ data plane
(csrc/kv_transfer.cpp, built via llmd_tpu.native) serves the same protocol for the
byte-moving hot path with the Python implementation as fallback.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

MAGIC = b"KVT1"


# ---------------------------------------------------------------------------
# Device↔host block staging
# ---------------------------------------------------------------------------


def extract_blocks(cache, page_ids: list[int], pages_per_layer: Optional[int] = None) -> np.ndarray:
    """Gather logical pages from the device cache into one contiguous host buffer.

    cache: flat layer-folded pool [L*P, ps, 2Hk, Dhp] (P = pages_per_layer; None =
    single-layer pool) → returns [n, L, ps, 2Hk, Dhp] (block-major so each block is
    a contiguous byte range — streamable/sliceable without repacking).
    """
    import jax
    import jax.numpy as jnp

    P = pages_per_layer or cache.shape[0]
    L = cache.shape[0] // P
    pids = np.asarray(page_ids, np.int32)
    rows = np.arange(L)[:, None] * P + pids[None, :]  # [L, n]
    arr = np.asarray(jax.device_get(cache[jnp.asarray(rows)]))  # [L, n, ps, 2Hk, Dhp]
    return np.ascontiguousarray(np.moveaxis(arr, 1, 0))


def insert_blocks(cache, page_ids: list[int], blocks: np.ndarray,
                  pages_per_layer: Optional[int] = None):
    """Write pulled blocks ([n, L, ps, 2Hk, Dhp]) into device pages; returns new cache."""
    import jax.numpy as jnp

    P = pages_per_layer or cache.shape[0]
    L = cache.shape[0] // P
    pids = np.asarray(page_ids, np.int32)
    rows = np.arange(L)[:, None] * P + pids[None, :]  # [L, n]
    dev = jnp.asarray(np.moveaxis(blocks, 0, 1))
    if cache.dtype == jnp.float8_e4m3fn and dev.dtype != cache.dtype:
        # heterogeneous P/D pair (peer shipped wider KV): e4m3 has no inf, so
        # a bare convert turns out-of-range values into nan and poisons the
        # page — clamp exactly like the engine's own write path
        from llmd_tpu.models.transformer import _FP8_MAX

        dev = jnp.clip(dev.astype(jnp.float32), -_FP8_MAX, _FP8_MAX)
    return cache.at[jnp.asarray(rows)].set(dev.astype(cache.dtype))


# ---------------------------------------------------------------------------
# Transfer params (the vLLM kv_transfer_params analogue, JSON-serializable)
# ---------------------------------------------------------------------------


@dataclass
class KVTransferParams:
    """Carried in request/response bodies between sidecar, P and D engines."""

    do_remote_decode: bool = False  # request to P: keep KV, return transfer handle
    do_remote_prefill: bool = False  # request to D: pull KV before compute
    do_prefix_pull: bool = False  # KV-plane: pull a cached prefix from a peer engine
    remote_host: Optional[str] = None
    remote_port: Optional[int] = None
    remote_request_id: Optional[str] = None
    num_blocks: int = 0
    block_hashes: list[int] = field(default_factory=list)  # prefix chain to pull
    tier: str = "peer"  # prefix-pull source: "peer" engine | "durable" store

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "KVTransferParams":
        d = d or {}
        return cls(
            do_remote_decode=bool(d.get("do_remote_decode")),
            do_remote_prefill=bool(d.get("do_remote_prefill")),
            do_prefix_pull=bool(d.get("do_prefix_pull")),
            remote_host=d.get("remote_host"),
            remote_port=d.get("remote_port"),
            remote_request_id=d.get("remote_request_id"),
            num_blocks=int(d.get("num_blocks", 0)),
            block_hashes=[int(h) for h in d.get("block_hashes") or []],
            tier=str(d.get("tier") or "peer"),
        )

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items()
                if v not in (None, False, 0) and v != []}


# ---------------------------------------------------------------------------
# Producer side: exported-block registry + side-channel server
# ---------------------------------------------------------------------------


@dataclass
class ExportedKV:
    block_hashes: list[int]
    token_chunks: list[list[int]]
    payload: bytes  # contiguous staging buffer (n blocks back-to-back)
    dtype: str
    block_shape: tuple[int, ...]  # [L, ps, 2Hk, Dhp]
    created: float = field(default_factory=time.monotonic)


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def _pull_header(block_hashes, token_chunks, dtype: str, block_shape, nbytes: int) -> bytes:
    """The pull-response header — ONE composer shared by both transports."""
    return json.dumps({
        "found": True, "block_hashes": list(block_hashes),
        "token_chunks": [list(c) for c in token_chunks], "dtype": dtype,
        "block_shape": list(block_shape), "nbytes": nbytes,
    }).encode()


class KVTransferSource:
    """Prefill-side export registry + TCP pull server.

    Protocol (shared by both transports):
      request:  MAGIC ‖ u32 len ‖ JSON {"op": "pull"|"pull_prefix"|"notify",
                                        "id": str, "hashes"?: [int]}
      response: u32 len ‖ JSON header ‖ payload[header["nbytes"]]

    ``transport``: "native" = C++ data plane (csrc/kv_transfer.cpp — serving runs off
    the GIL, the NIXL-role component), "python" = threaded sockets, "auto" = native
    with Python fallback.

    ``prefix_provider`` (KV plane): optional callback
    ``(block_hashes, request_id) -> Optional[(hashes, token_chunks, blocks)]``
    that resolves an on-demand prefix export for a ``pull_prefix`` request. The
    C++ transport does not speak this op, so under ``transport="auto"`` a set
    provider selects the Python transport.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0, ttl_s: float = 120.0,
                 transport: str = "auto") -> None:
        self.host, self.port = host, port
        self.ttl_s = ttl_s  # outlives the sidecar idle window (tpu patch keep-alive 120s)
        self.transport = transport
        self.prefix_provider = None  # set BEFORE start() to serve pull_prefix
        self.native = None  # (lib, handle) when the C++ server is live
        self.exports: dict[str, ExportedKV] = {}
        self._lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._stats = {"exports": 0, "pulls": 0, "notifies": 0, "expired": 0, "misses": 0}

    @property
    def stats(self) -> dict[str, int]:
        if self.native is not None:
            lib, h = self.native
            return {k: int(lib.kvt_stat(h, k.encode()))
                    for k in ("exports", "pulls", "notifies", "expired", "misses")}
        with self._lock:  # snapshot: serving threads bump these counters
            return dict(self._stats)

    # -- registry ----------------------------------------------------------
    def register(self, request_id: str, block_hashes: list[int],
                 token_chunks: list[list[int]], blocks: np.ndarray) -> int:
        payload = blocks.tobytes()
        if self.native is not None:
            lib, h = self.native
            hdr = _pull_header(block_hashes, token_chunks, str(blocks.dtype),
                               blocks.shape[1:], len(payload))
            lib.kvt_register(h, request_id.encode(), hdr, len(hdr), payload, len(payload))
            return len(payload)
        ex = ExportedKV(
            block_hashes=list(block_hashes),
            token_chunks=[list(c) for c in token_chunks],
            payload=payload,
            dtype=str(blocks.dtype),
            block_shape=tuple(blocks.shape[1:]),
        )
        with self._lock:
            self.exports[request_id] = ex
            self._stats["exports"] += 1
        return len(ex.payload)

    def release(self, request_id: str) -> None:
        if self.native is not None:
            lib, h = self.native
            lib.kvt_release(h, request_id.encode())
            return
        with self._lock:
            self.exports.pop(request_id, None)

    def __len__(self) -> int:
        if self.native is not None:
            lib, h = self.native
            return int(lib.kvt_count(h))
        with self._lock:
            return len(self.exports)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        prefer_native = (self.transport == "native"
                         or (self.transport == "auto" and self.prefix_provider is None))
        if prefer_native and self._start_native():
            return
        if self.transport == "native":
            raise RuntimeError("native kv_transfer transport unavailable (g++ build failed)")
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, self.port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._srv.settimeout(0.25)
        t = threading.Thread(target=self._accept_loop, name="kvt-accept", daemon=True)
        t.start()
        self._threads.append(t)
        r = threading.Thread(target=self._reaper, name="kvt-reaper", daemon=True)
        r.start()
        self._threads.append(r)

    def _start_native(self) -> bool:
        import ctypes

        from llmd_tpu.native import load_library

        lib = load_library("kv_transfer")
        if lib is None:
            return False
        lib.kvt_server_create.restype = ctypes.c_void_p
        lib.kvt_server_create.argtypes = [ctypes.c_int]
        lib.kvt_server_port.argtypes = [ctypes.c_void_p]
        lib.kvt_register.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_long,
        ]
        lib.kvt_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kvt_count.argtypes = [ctypes.c_void_p]
        lib.kvt_reap.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.kvt_stat.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kvt_stat.restype = ctypes.c_long
        lib.kvt_server_destroy.argtypes = [ctypes.c_void_p]
        h = lib.kvt_server_create(self.port)
        if not h:
            return False
        self.native = (lib, h)
        self.port = int(lib.kvt_server_port(h))
        r = threading.Thread(target=self._native_reaper, name="kvt-reaper", daemon=True)
        r.start()
        self._threads.append(r)
        return True

    def stop(self) -> None:
        self._stop.set()
        if self.native is not None:
            lib, h = self.native
            self.native = None
            lib.kvt_server_destroy(h)
        if self._srv is not None:
            self._srv.close()

    def _native_reaper(self) -> None:
        while not self._stop.wait(min(5.0, self.ttl_s / 4)):
            if self.native is None:
                return
            lib, h = self.native
            lib.kvt_reap(h, self.ttl_s)

    def _reaper(self) -> None:
        while not self._stop.wait(min(5.0, self.ttl_s / 4)):
            cutoff = time.monotonic() - self.ttl_s
            with self._lock:
                dead = [rid for rid, ex in self.exports.items() if ex.created < cutoff]
                for rid in dead:
                    del self.exports[rid]
                    self._stats["expired"] += 1

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(30.0)
                # one connection may carry several requests (handshake reuse)
                while not self._stop.is_set():
                    try:
                        magic = _recv_exact(conn, 4)
                    except ConnectionError:
                        return
                    if magic != MAGIC:
                        return
                    (ln,) = struct.unpack(">I", _recv_exact(conn, 4))
                    req = json.loads(_recv_exact(conn, ln))
                    self._handle(conn, req)
        except Exception:
            pass  # connection-scoped failure; peer retries or recomputes

    def _handle(self, conn: socket.socket, req: dict) -> None:
        op, rid = req.get("op"), req.get("id", "")
        if op == "pull":
            with self._lock:
                ex = self.exports.get(rid)
                self._stats["pulls" if ex else "misses"] += 1
            if ex is None:
                hdr = json.dumps({"found": False, "nbytes": 0}).encode()
                conn.sendall(struct.pack(">I", len(hdr)) + hdr)
                return
            hdr = _pull_header(ex.block_hashes, ex.token_chunks, ex.dtype,
                               ex.block_shape, len(ex.payload))
            conn.sendall(struct.pack(">I", len(hdr)) + hdr)
            conn.sendall(ex.payload)
        elif op == "pull_prefix":
            provider = self.prefix_provider
            hashes = [int(h) for h in req.get("hashes") or []]
            res = None
            if provider is not None and hashes:
                try:
                    res = provider(hashes, rid)
                except Exception:
                    res = None  # provider failure → miss; puller re-prefills
            if res is None:
                with self._lock:
                    self._stats["misses"] += 1
                hdr = json.dumps({"found": False, "nbytes": 0}).encode()
                conn.sendall(struct.pack(">I", len(hdr)) + hdr)
                return
            got_hashes, chunks, blocks = res
            # register under the PULLER's request id: the entry is freed by its
            # notify (or abort-release/TTL) exactly like a P/D export, and is
            # visible in len()/the transfer_registrations gauge meanwhile
            self.register(rid, got_hashes, chunks, blocks)
            with self._lock:
                ex = self.exports[rid]
                self._stats["pulls"] += 1
            hdr = _pull_header(ex.block_hashes, ex.token_chunks, ex.dtype,
                               ex.block_shape, len(ex.payload))
            conn.sendall(struct.pack(">I", len(hdr)) + hdr)
            conn.sendall(ex.payload)
        elif op == "notify":
            with self._lock:
                self.exports.pop(rid, None)
                self._stats["notifies"] += 1
            hdr = json.dumps({"ok": True, "nbytes": 0}).encode()
            conn.sendall(struct.pack(">I", len(hdr)) + hdr)


# ---------------------------------------------------------------------------
# Consumer side
# ---------------------------------------------------------------------------


@dataclass
class PulledKV:
    block_hashes: list[int]
    token_chunks: list[list[int]]
    blocks: np.ndarray  # [n, L, ps, 2Hk, Dhp]


class KVTransferClient:
    """Decode-side puller (blocking; callers run it in an executor thread)."""

    def __init__(self, timeout_s: float = 30.0) -> None:
        self.timeout_s = timeout_s

    def _request(self, host: str, port: int, req: dict) -> tuple[dict, bytes]:
        with socket.create_connection((host, port), timeout=self.timeout_s) as conn:
            body = json.dumps(req).encode()
            conn.sendall(MAGIC + struct.pack(">I", len(body)) + body)
            (ln,) = struct.unpack(">I", _recv_exact(conn, 4))
            hdr = json.loads(_recv_exact(conn, ln))
            payload = _recv_exact(conn, hdr.get("nbytes", 0)) if hdr.get("nbytes") else b""
            return hdr, payload

    @staticmethod
    def _decode(hdr: dict, payload: bytes) -> Optional[PulledKV]:
        if not hdr.get("found"):
            return None
        shape = tuple(hdr["block_shape"])
        n = len(hdr["block_hashes"])
        blocks = np.frombuffer(payload, dtype=np.dtype(hdr["dtype"])).reshape((n,) + shape)
        return PulledKV(hdr["block_hashes"], hdr["token_chunks"], blocks)

    def pull(self, host: str, port: int, request_id: str) -> Optional[PulledKV]:
        hdr, payload = self._request(host, port, {"op": "pull", "id": request_id})
        return self._decode(hdr, payload)

    def pull_prefix(self, host: str, port: int, request_id: str,
                    block_hashes: Sequence[int]) -> Optional[PulledKV]:
        """KV-plane pull: ask a peer engine for whatever prefix of the given
        block-hash chain it still holds. One round trip — the peer resolves,
        registers (under ``request_id``), and serves in the same response."""
        hdr, payload = self._request(host, port, {
            "op": "pull_prefix", "id": request_id,
            "hashes": [int(h) for h in block_hashes]})
        return self._decode(hdr, payload)

    def notify(self, host: str, port: int, request_id: str) -> bool:
        try:
            hdr, _ = self._request(host, port, {"op": "notify", "id": request_id})
            return bool(hdr.get("ok"))
        except OSError:
            return False  # producer gone; its TTL reaper cleans up


# ---------------------------------------------------------------------------
# Engine-side connector glue
# ---------------------------------------------------------------------------


def stage_pages(cache, page_ids: list[int], pages_per_layer: int,
                staging_pages: int = 16) -> list:
    """Dispatch chunked device gathers of the given pages with async host-copy
    hints; returns the in-flight device parts ([L, n_i, ps, 2Hk, Dhp] each).
    Cheap (no sync) — safe under the engine lock; reads the cache value as of
    dispatch, so later donated steps cannot corrupt the staging."""
    import jax.numpy as jnp

    L = cache.shape[0] // pages_per_layer
    lrows = np.arange(L)[:, None]
    parts: list = []
    for i in range(0, len(page_ids), max(1, staging_pages)):
        pg = np.asarray(page_ids[i : i + staging_pages], np.int32)
        part = cache[jnp.asarray(lrows * pages_per_layer + pg[None, :])]
        try:
            part.copy_to_host_async()  # start D2H now; the drain happens later
        except (AttributeError, RuntimeError):
            pass
        parts.append(part)
    return parts


def drain_staged(parts: list) -> np.ndarray:
    """Blocking half: collect staged parts into one contiguous block-major
    host buffer ([n, L, ps, 2Hk, Dhp]). Run OFF the engine lock."""
    import jax

    return np.ascontiguousarray(np.concatenate(
        [np.moveaxis(np.asarray(jax.device_get(p)), 1, 0) for p in parts], axis=0))


@dataclass
class StagedExport:
    """In-flight device→host staging for one request's KV export.

    ``parts`` are device-resident chunk gathers ([L, n_i, ps, 2Hk, Dhp]) with
    device→host copies already started — the engine lock can be released the
    moment this object exists; the bytes stream back while the engine keeps
    stepping (the async analogue of the reference's pinned-staging DMA overlap,
    kv-offloader.md:33-40)."""

    request_id: str
    hashes: list[int]
    chunks: list[list[int]]
    parts: list[Any]


def export_begin(engine, request_id: str, token_ids: list[int],
                 lora_id: Optional[str] = None,
                 staging_pages: int = 16,
                 mm_hashes: Sequence[bytes] = ()) -> tuple[KVTransferParams, Optional[StagedExport]]:
    """Phase 1 (caller holds the engine lock, cheap): resolve the resident block
    chain and DISPATCH chunked device gathers with async host copies. The gathers
    read the cache value as of dispatch, so later steps/evictions can't corrupt
    the export — the runtime orders the donated step after these reads."""
    from llmd_tpu.core.kv_events import block_keys_for_tokens

    ps = engine.cfg.page_size
    # generation-scoped lora key + media hashes, so exported keys line up with
    # the engine's own committed blocks (kv_manager.maybe_commit_blocks folds
    # BOTH into every block hash)
    keys = block_keys_for_tokens(token_ids, ps, engine._lora_hash_key(lora_id),
                                 mm_hashes)
    pids: list[int] = []
    hashes: list[int] = []
    chunks: list[list[int]] = []
    for i, h in enumerate(keys):
        pid = engine.alloc.cached.get(h)
        if pid is None:
            break  # chain broken (block evicted already) — export the resident prefix
        pids.append(pid)
        hashes.append(h)
        chunks.append(token_ids[i * ps : (i + 1) * ps])
    params = KVTransferParams(remote_request_id=request_id, num_blocks=len(pids))
    if not pids:
        return params, None
    parts = stage_pages(engine.cache, pids, engine.cfg.num_pages, staging_pages)
    return params, StagedExport(request_id, hashes, chunks, parts)


def prefix_export_begin(engine, request_id: str, block_hashes: Sequence[int],
                        staging_pages: int = 16) -> Optional[StagedExport]:
    """Phase 1 of serving a cross-engine prefix pull (caller holds the engine
    lock, cheap): walk the requested hash chain against the local prefix cache
    and dispatch staged gathers for the resident prefix. The allocator retains
    block hashes but not token chunks, so chunks ship empty — the puller
    verifies the chain against its own prompt and fills chunks from it."""
    pids: list[int] = []
    hashes: list[int] = []
    for h in block_hashes:
        pid = engine.alloc.cached.get(int(h))
        if pid is None:
            break  # chain broken locally — serve the resident prefix only
        pids.append(pid)
        hashes.append(int(h))
    if not pids:
        return None
    parts = stage_pages(engine.cache, pids, engine.cfg.num_pages, staging_pages)
    return StagedExport(request_id, hashes, [[] for _ in hashes], parts)


def export_finish(staged: StagedExport, source: KVTransferSource) -> int:
    """Phase 2 (engine lock NOT held): drain the staged copies into one
    contiguous block-major buffer and register the export. Returns blocks."""
    blocks = drain_staged(staged.parts)
    source.register(staged.request_id, staged.hashes, staged.chunks, blocks)
    return blocks.shape[0]


def export_from_engine(engine, source: KVTransferSource, request_id: str,
                       token_ids: list[int], lora_id: Optional[str] = None,
                       mm_hashes: Sequence[bytes] = ()) -> KVTransferParams:
    """Synchronous convenience wrapper (tests / non-threaded callers): both
    phases back to back under whatever locking the caller provides."""
    params, staged = export_begin(engine, request_id, token_ids, lora_id,
                                  mm_hashes=mm_hashes)
    if staged is not None:
        export_finish(staged, source)
    return params


def inject_into_engine(engine, pulled: PulledKV, token_ids: list[int],
                       lora_id: Optional[str] = None,
                       mm_hashes: Sequence[bytes] = ()) -> int:
    """Commit pulled blocks into the local allocator + cache as prefix-cache entries
    (caller holds the engine lock). Returns blocks injected.

    Hash-chain verification: only blocks matching the locally recomputed chain for
    THIS prompt are accepted — a stale/foreign export cannot poison the cache.
    """
    from llmd_tpu.core.kv_events import block_keys_for_tokens

    ps = engine.cfg.page_size
    L = engine.cache.shape[0] // engine.cfg.num_pages
    local_shape = (L,) + engine.cache.shape[1:]
    if pulled.blocks.shape[1:] != local_shape:
        # heterogeneous P/D pair: peer runs a different pool layout (padded vs
        # packed) or page geometry — dtype converts fine (insert_blocks) but a
        # shape mismatch cannot; refuse LOUDLY so a mixed-version rollout reads
        # as a config error, not silent 100% recompute under pull_failures
        raise ValueError(
            f"pulled KV block shape {pulled.blocks.shape[1:]} does not match "
            f"local pool block shape {local_shape} — P/D peers must agree on "
            "kv_layout and page geometry (rolling upgrades: pin kv_layout)")
    lora_key = engine._lora_hash_key(lora_id)
    keys = block_keys_for_tokens(token_ids, ps, lora_key, mm_hashes)
    take: list[tuple[int, int]] = []  # (pulled_idx, page_id)
    parent_of: dict[int, Optional[int]] = {}
    parent: Optional[int] = None
    for i, h in enumerate(pulled.block_hashes):
        if i >= len(keys) or keys[i] != h:
            break
        parent_of[h] = parent
        parent = h
        if h in engine.alloc.cached:
            continue  # already resident locally
        pid = engine.alloc.allocate()
        if pid is None:
            break  # pool pressure: keep what we have, recompute the rest
        take.append((i, pid))
    if not take:
        return 0
    idxs = [i for i, _ in take]
    pids = [p for _, p in take]
    engine.cache = insert_blocks(engine.cache, pids, pulled.blocks[idxs], engine.cfg.num_pages)
    for i, pid in take:
        h = pulled.block_hashes[i]
        # prefix pulls ship empty chunks (the peer's allocator doesn't retain
        # them); the verified hash chain proves the local prompt slice is the
        # exact token content of the block
        chunk = list(pulled.token_chunks[i]) or token_ids[i * ps : (i + 1) * ps]
        engine.alloc.commit_block(pid, h, chunk, parent_of[h], lora_key)
        engine.alloc.release(pid)  # refcount 0 → cached/evictable, like any prefix hit
    return len(take)
