"""P/D disaggregation: KV-transfer engine + routing sidecar.

Parity: reference docs/architecture/advanced/disaggregation/README.md — the routing
sidecar (104-131) and the KV transfer layer (133-178, NIXL on GPU; TPUConnectorHMA's
host-memory-assisted TCP path on TPU). Ours is the TPU-native design: device→host
contiguous staging, pull-model side channel, recompute-on-failure.
"""

from llmd_tpu.disagg.transfer import (
    KVTransferClient,
    KVTransferParams,
    KVTransferSource,
    extract_blocks,
    insert_blocks,
)
from llmd_tpu.disagg.sidecar import RoutingSidecar
from llmd_tpu.disagg.encode import EncodeServer, VisionRunner  # noqa: F401

__all__ = [
    "EncodeServer",
    "KVTransferClient",
    "KVTransferParams",
    "KVTransferSource",
    "RoutingSidecar",
    "VisionRunner",
    "extract_blocks",
    "insert_blocks",
]
