"""Encode worker: the E stage of E/PD and E/P/D multimodal disaggregation.

The reference offloads multimodal encoding (media → embeddings) to dedicated
workers; multiple entries in one request encode concurrently on different
workers, and the resulting embeddings are consumed by prefill/decode alongside
text tokens (`guides/multimodal-serving/e-disaggregation/README.md`).

TPU shape of the same idea:
- one jitted vision-tower program (models/vision.py) batched over the media
  items of a request — N items compile once and ride the MXU together;
- a stateless HTTP worker (`POST /v1/encode`) returning
  ``{items: [{mm_hash, n_tokens, embedding_b64}]}``; the sidecar fans request
  media out across workers and attaches the rows as ``mm_items`` for the P/D
  engines (engine-side injection: models/transformer.forward_core mm path);
- a content-hash LRU so re-sent media (multi-turn chats re-uploading the same
  image) skip the tower entirely — the encode analogue of prefix caching.

Vision params are derived deterministically from the model name, so every
encode worker for a model produces identical embeddings — interchangeable
workers, exactly like the reference's encode pool.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
from collections import OrderedDict
from typing import Optional

import numpy as np
from aiohttp import web

from llmd_tpu.models.config import ModelConfig
from llmd_tpu.models.vision import (
    bytes_to_pixels,
    encode_images,
    init_vision_params,
    mm_content_hash,
)


def is_media_part(part) -> bool:
    """Cheap media detection: inline ``data:`` URI of a known kind. Does NOT
    decode the payload — detection runs on event loops where materializing a
    64 MB base64 body would stall every concurrent stream. Delegates to the
    ONE media predicate in core.request so router hashing and engine handling
    can never disagree about what counts as media."""
    from llmd_tpu.core.request import part_is_inline_media

    return part_is_inline_media(part)


def part_identity(part: dict) -> bytes:
    """Canonical media identity used EVERYWHERE a media hash is compared:
    router-side block keys (core/request._mm_hash over the URI string), the
    encode wire format, engine block-key folds, and P/D transfer. One function
    or prefix-cache affinity silently breaks for every multimodal request."""
    from llmd_tpu.core.request import _mm_hash

    h = _mm_hash(part)
    return h if h is not None else hashlib.sha256(b"media").digest()


def iter_media_parts(body: dict):
    """Yield the media content parts of an OpenAI-style request body, in prompt
    order — the ONE traversal shared by the sidecar's E-stage fan-out and the
    engine server's VL detection/tokenization (they must agree on what counts
    as media or E/PD and combined-PD diverge)."""
    for m in body.get("messages", []) or []:
        content = m.get("content")
        if isinstance(content, list):
            for part in content:
                if is_media_part(part):
                    yield part


def media_bytes_from_part(part: dict) -> Optional[bytes]:
    """OpenAI-style content part → raw media bytes (data: URIs only — this
    environment has no egress; remote URLs are the caller's job to inline)."""
    from llmd_tpu.core.request import media_url_of_part

    _kind, url = media_url_of_part(part)
    if url is not None and url.startswith("data:"):
        try:
            return base64.b64decode(url.split(",", 1)[1], validate=False)
        except (IndexError, binascii.Error):
            return None
    return None


class VisionRunner:
    """Jitted vision tower + content-hash LRU (shared by encode workers and
    combined-PD servers that encode in-process)."""

    def __init__(self, cfg: ModelConfig, cache_items: int = 256) -> None:
        import threading

        import jax

        if not cfg.has_vision:
            raise ValueError(f"model {cfg.name!r} has no vision tower")
        self.cfg = cfg
        # encode() runs on executor threads (the worker keeps its event loop
        # free); the LRU + stats need the lock once calls overlap
        self._lock = threading.Lock()
        seed = int.from_bytes(
            hashlib.sha256(f"vision:{cfg.name}".encode()).digest()[:4], "little")
        self.params = init_vision_params(cfg, jax.random.PRNGKey(seed))
        self._fn = jax.jit(lambda px: encode_images(cfg, self.params, px))
        self._lru: OrderedDict[bytes, np.ndarray] = OrderedDict()
        self._cache_items = cache_items
        self.stats = {"encoded_items": 0, "cache_hits": 0}

    def encode(self, payloads: list[bytes]) -> list[tuple[bytes, np.ndarray]]:
        """bytes per media item → [(content_hash, [mm_tokens, hidden] f32)]."""
        out: list[Optional[tuple[bytes, np.ndarray]]] = [None] * len(payloads)
        fresh: list[tuple[int, bytes, bytes]] = []  # (slot, hash, payload)
        with self._lock:
            for i, data in enumerate(payloads):
                h = mm_content_hash(data)
                hit = self._lru.get(h)
                if hit is not None:
                    self._lru.move_to_end(h)
                    self.stats["cache_hits"] += 1
                    out[i] = (h, hit)
                else:
                    fresh.append((i, h, data))
        if fresh:
            px = np.stack([bytes_to_pixels(self.cfg, d) for _, _, d in fresh])
            emb = np.asarray(self._fn(px), np.float32)  # [n, mm_tokens, hidden]
            with self._lock:
                for (i, h, _), e in zip(fresh, emb):
                    out[i] = (h, e)
                    self._lru[h] = e
                    if len(self._lru) > self._cache_items:
                        self._lru.popitem(last=False)
                self.stats["encoded_items"] += len(fresh)
        return out  # type: ignore[return-value]


def mm_item_to_wire(h: bytes, emb: np.ndarray) -> dict:
    return {
        "mm_hash": h.hex(),
        "n_tokens": int(emb.shape[0]),
        "embedding_b64": base64.b64encode(
            np.ascontiguousarray(emb, np.float32).tobytes()).decode(),
    }


def mm_item_from_wire(d: dict, hidden_size: int) -> tuple[bytes, np.ndarray]:
    emb = np.frombuffer(base64.b64decode(d["embedding_b64"]), np.float32)
    return bytes.fromhex(d["mm_hash"]), emb.reshape(int(d["n_tokens"]), hidden_size)


class EncodeServer:
    """Standalone encode worker (the reference's encode-deployment.yaml role)."""

    def __init__(self, cfg: ModelConfig, host: str = "127.0.0.1", port: int = 0) -> None:
        self.cfg = cfg
        self.host, self.port = host, port
        self.runner_ = VisionRunner(cfg)
        self._runner: Optional[web.AppRunner] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_post("/v1/encode", self._encode)
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _health(self, request: web.Request):
        return web.json_response({"status": "ok", "role": "encode"})

    async def _metrics(self, request: web.Request):
        s = self.runner_.stats
        body = (
            f'llmd_tpu:encode_items_total {s["encoded_items"]}\n'
            f'llmd_tpu:encode_cache_hits_total {s["cache_hits"]}\n'
        )
        return web.Response(text=body, content_type="text/plain")

    async def _encode(self, request: web.Request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)
        parts = body.get("items", [])
        payloads: list[bytes] = []
        for part in parts:
            data = media_bytes_from_part(part)
            if data is None:
                return web.json_response(
                    {"error": "unsupported media part (inline data: URIs only)"},
                    status=400)
            payloads.append(data)
        import asyncio

        # executor thread: the tower (jit compile on first call + device
        # compute) must not block the worker's event loop — health probes and
        # concurrent fan-out items keep flowing while this batch encodes
        encoded = await asyncio.get_running_loop().run_in_executor(
            None, self.runner_.encode, payloads)
        # wire identity = the canonical part hash (what router + engine fold
        # into block keys); the runner's content-hash only keys its own LRU
        return web.json_response(
            {"items": [mm_item_to_wire(part_identity(p), e)
                       for p, (_h, e) in zip(parts, encoded)]})


def main() -> None:
    """CLI: python -m llmd_tpu.disagg.encode --model tiny-vl --port 8001

    Deployment entrypoint for an encode worker pod (the reference's
    encode-deployment.yaml role, guides/multimodal-serving/e-disaggregation)."""
    import argparse
    import asyncio

    from llmd_tpu.models import get_model_config

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny-vl",
                    help="registry shape with a vision tower (mm_tokens > 0)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8001)
    args = ap.parse_args()

    srv = EncodeServer(get_model_config(args.model), host=args.host, port=args.port)

    async def run() -> None:
        await srv.start()
        print(f"llmd-tpu encode worker ({args.model}) on http://{srv.address}",
              flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
