"""Pool controller: the reconcile loop that closes the autoscaling loop.

The policy layer (``autoscaling/wva.py``, ``autoscaling/hpa.py``) has always
been able to *decide* replica counts; this controller makes the decisions
real and feeds them real inputs:

- **live metrics in** — ``PoolMetrics`` is built from the router's endpoint
  pool (the attrs the MetricsPoller scrapes under the ``StdMetric`` keys)
  plus the flow-control queue depth as the EPP queue signal, not hand-built
  fixtures;
- **lifecycle out** — scale-up launches replicas through a
  :class:`~llmd_tpu.pool.launcher.ReplicaLauncher` (fakes in CI, engine
  subprocesses on device) and registers them with router discovery
  (``EndpointPool.upsert``), so the datalayer, scheduler, and breakers track
  the live set; scale-down marks the victim draining, runs the PR-3 ``POST
  /drain`` handshake, deregisters, then stops the process;
- **scale-to-zero / scale-from-zero** — the WVA enforcer's retention window
  drives 0, the fast tick watches the flow queue and launches 1 the moment
  requests pile up at an empty pool (flow control holds dispatch while the
  pool is empty, so nothing is lost); launches are warm when the snapshot
  store has the config fingerprint, and every launch reports its duration
  to the ``llmd_tpu:pool_warm_start_seconds`` histogram by kind;
- **self-healing** — a periodic ``/health`` probe retires dead replicas
  (killed processes, not drained ones) and the next reconcile replaces
  them, which is what lets chaos tooling kill replicas mid-traffic.

All knobs are ``LLMD_POOL_*`` env vars (deploy/ENV_VARS.md) with
constructor overrides for tests.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from llmd_tpu.autoscaling.hpa import HPAEvaluator
from llmd_tpu.autoscaling.wva import (
    Enforcer,
    PoolMetrics,
    ReplicaMetrics,
    Variant,
    WVAEngine,
)
from llmd_tpu.core.endpoint import Endpoint, EndpointPool
from llmd_tpu.core.metrics_contract import StdMetric
from llmd_tpu.pool.launcher import ReplicaHandle, ReplicaLauncher


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class PoolConfig:
    """Controller knobs (env-backed; see deploy/ENV_VARS.md)."""

    model: str = "fake/model"
    min_replicas: int = 1
    max_replicas: int = 4
    scale_to_zero: bool = False
    retention_s: float = 600.0  # idle window before scale-to-zero
    interval_s: float = 30.0  # full analyze/reconcile cadence
    sfz_interval_s: float = 0.1  # scale-from-zero fast-tick cadence
    drain_timeout_s: float = 10.0
    ready_timeout_s: float = 60.0
    policy: str = "max"  # "wva" | "hpa" | "max" (max of both)
    health_timeout_s: float = 1.0
    role: str = "both"  # prefill | decode | both — stamped on Endpoints

    @classmethod
    def from_env(cls, **overrides: Any) -> "PoolConfig":
        cfg = cls(
            min_replicas=_env_i("LLMD_POOL_MIN_REPLICAS", 1),
            max_replicas=_env_i("LLMD_POOL_MAX_REPLICAS", 4),
            scale_to_zero=os.environ.get("LLMD_POOL_SCALE_TO_ZERO", "0")
            not in ("0", "", "false", "False"),
            retention_s=_env_f("LLMD_POOL_RETENTION_S", 600.0),
            interval_s=_env_f("LLMD_POOL_INTERVAL_S", 30.0),
            drain_timeout_s=_env_f("LLMD_POOL_DRAIN_TIMEOUT_S", 10.0),
            ready_timeout_s=_env_f("LLMD_POOL_READY_TIMEOUT_S", 60.0),
            policy=os.environ.get("LLMD_POOL_POLICY", "max"),
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


def replica_metrics_from_endpoint(ep: Endpoint) -> ReplicaMetrics:
    """One live endpoint's scraped attrs → the WVA analyzer's input row."""
    kv = ep.metric(StdMetric.KV_UTILIZATION)
    num_blocks = int(ep.metric(StdMetric.NUM_BLOCKS) or 0)
    block_size = int(ep.metric(StdMetric.BLOCK_SIZE) or 16)
    return ReplicaMetrics(
        kv_usage=kv,
        queue_len=ep.metric(StdMetric.QUEUED_REQUESTS),
        num_blocks=num_blocks,
        block_size=block_size,
        tokens_in_use=kv * num_blocks * block_size,
    )


@dataclass
class LaunchRecord:
    kind: str  # "cold" | "warm"
    seconds: float
    address: str


class PoolController:
    """Reconcile loop for one model pool (one WVA variant).

    ``router`` (a RouterServer) is optional but is the production wiring:
    it supplies discovery (``router.pool``), the drain/breaker integration
    (``router.resilience``), the flow queue depth (EPP queue signal), the
    shared metrics registry, and the flight recorder. Unit tests can pass a
    bare ``EndpointPool`` and stubs instead.
    """

    def __init__(self, cfg: PoolConfig, launcher: ReplicaLauncher,
                 pool: Optional[EndpointPool] = None, router: Any = None,
                 registry: Any = None, flight: Any = None,
                 flow_depth_fn: Optional[Callable[[], float]] = None) -> None:
        self.cfg = cfg
        self.launcher = launcher
        self.router = router
        self.pool = pool if pool is not None else (
            router.pool if router is not None else EndpointPool())
        self.resilience = getattr(router, "resilience", None)
        # fleet rollup (obs/fleet.py): when the router aggregates replica
        # scrapes, the controller consumes the rollup instead of re-summing
        # per-replica attributes on every reconcile tick
        self.fleet = getattr(router, "fleet", None)
        self.flight = flight if flight is not None else getattr(
            router, "flight", None)
        if flow_depth_fn is not None:
            self._flow_depth = flow_depth_fn
        elif router is not None and getattr(router, "flow", None) is not None:
            self._flow_depth = router.flow._total_queued
        else:
            self._flow_depth = lambda: 0.0

        self.replicas: dict[str, ReplicaHandle] = {}
        self.launch_records: list[LaunchRecord] = []
        self._last_traffic = time.monotonic()
        self._lock = asyncio.Lock()
        self._task: Optional[asyncio.Task] = None
        self._session = None  # aiohttp session for drain/health probes

        self.variant = Variant(
            name=f"{cfg.model}-pool", model_id=cfg.model,
            min_replicas=cfg.min_replicas, max_replicas=cfg.max_replicas,
            current_replicas=0, desired_replicas=0)
        self.wva = WVAEngine(
            pools={cfg.model: [self.variant]},
            metrics_fn=lambda _mid: self._pool_metrics(),
            enforcer=Enforcer(scale_to_zero=cfg.scale_to_zero,
                              retention_s=cfg.retention_s),
            interval_s=cfg.interval_s)
        self.hpa = HPAEvaluator(
            min_replicas=0 if cfg.scale_to_zero else cfg.min_replicas,
            max_replicas=cfg.max_replicas)

        registry = registry if registry is not None else getattr(
            router, "registry", None)
        self.families = None
        if registry is not None:
            from llmd_tpu.obs.metrics import register_pool_metrics

            self.families = register_pool_metrics(registry)
            self.families.desired_replicas.set_function(
                lambda: self.variant.desired_replicas)
            self.families.ready_replicas.set_function(
                lambda: len(self.replicas))

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession()
        if self.cfg.min_replicas > 0:
            self.variant.desired_replicas = self.cfg.min_replicas
            await self._reconcile("floor")
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        async with self._lock:
            for address in list(self.replicas):
                await self._deregister(address)
                await self.launcher.stop(self.replicas.pop(address))
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _loop(self) -> None:
        last_full = 0.0
        while True:
            await asyncio.sleep(self.cfg.sfz_interval_s)
            try:
                now = time.monotonic()
                if now - last_full >= self.cfg.interval_s:
                    last_full = now
                    await self.step()
                else:
                    await self._fast_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # one bad tick never kills the controller

    # -------------------------------------------------------------- signals
    def _predicted_latency(self, ep: Endpoint):
        """Router predictor state → (ttft_s, itl_s), None without a predictor.

        When the router runs the predicted-latency-producer, its model (or the
        composite heuristic while cold) turns the endpoint's scraped load into
        the latency estimates the WVA SLOAnalyzer consumes — so SLO-driven
        scaling sees the same predictor the scheduler scores with."""
        ctx = getattr(self.router, "ctx", None)
        predictor = ctx.get("latency_predictor") if ctx else None
        if predictor is None:
            return None
        from llmd_tpu.predictor.model import LatencySample, heuristic_latency

        sample = LatencySample(
            kv_usage=ep.metric(StdMetric.KV_UTILIZATION),
            input_len=0.0,
            queue_depth=ep.metric(StdMetric.QUEUED_REQUESTS),
            running_requests=ep.metric(StdMetric.RUNNING_REQUESTS),
            prefix_match_pct=0.0,
            inflight_tokens=ep.metric(StdMetric.WAITING_TOKENS))
        try:
            preds = predictor.predict([sample])
        except Exception:
            return None
        pred = preds[0] if preds else None
        if pred is None or pred[0] is None or pred[1] is None:
            pred = heuristic_latency(sample)
        return pred[0] / 1e3, pred[1] / 1e3  # ms → s

    def _live_metrics(self) -> list[ReplicaMetrics]:
        out = []
        for address in self.replicas:
            ep = self.pool.get(address)
            if ep is not None and ep.ready:
                rm = replica_metrics_from_endpoint(ep)
                pred = self._predicted_latency(ep)
                if pred is not None:
                    rm.avg_ttft_s, rm.avg_itl_s = pred
                out.append(rm)
        return out

    def _running_total(self) -> float:
        if self.fleet is not None and len(self.fleet) > 0:
            return self.fleet.running_total()
        return sum(
            self.pool.get(a).metric(StdMetric.RUNNING_REQUESTS)
            for a in self.replicas if self.pool.get(a) is not None)

    def _pool_metrics(self) -> PoolMetrics:
        reps = self._live_metrics()
        depth = float(self._flow_depth())
        busy = depth > 0 or any(r.queue_len > 0 for r in reps) \
            or self._running_total() > 0
        now = time.monotonic()
        if busy:
            self._last_traffic = now
        in_retention = 1.0 if (now - self._last_traffic
                               <= self.cfg.retention_s) else 0.0
        return PoolMetrics(
            replicas={self.variant.name: reps},
            epp_queue_size=depth,
            requests_in_retention=in_retention)

    # ---------------------------------------------------------------- steps
    async def step(self) -> None:
        """One full pass: health-check, analyze (WVA + HPA), reconcile."""
        await self._health_sweep()
        self.variant.current_replicas = len(self.replicas)
        self.variant.pending_replicas = 0
        before = self.variant.desired_replicas
        reason = "steady"

        if self.cfg.policy in ("wva", "max"):
            signals = self.wva.step()
            sig = signals.get(self.cfg.model)
            if sig is not None and self.variant.desired_replicas != before:
                reason = "wva_saturated" if sig.scale_up else "wva_spare"
        if self.cfg.policy in ("hpa", "max"):
            want_hpa = self.hpa.desired_replicas(
                max(1, len(self.replicas)),
                {"igw_queue_depth": float(self._flow_depth()),
                 "igw_running_requests": self._running_total()})
            if self.cfg.policy == "hpa":
                self.variant.desired_replicas = want_hpa
                reason = "hpa"
            elif want_hpa > self.variant.desired_replicas:
                self.variant.desired_replicas = want_hpa
                reason = "hpa"
        if (self.cfg.scale_to_zero and before > 0
                and self.variant.desired_replicas == 0):
            reason = "scale_to_zero"
        await self._reconcile(reason)

    async def _fast_tick(self) -> None:
        """Scale-from-zero fast path (WVA's 100ms loop analogue)."""
        if self.replicas or self.variant.desired_replicas > 0:
            return
        self.variant.current_replicas = 0
        self.wva.scale_from_zero_step()
        if self.variant.desired_replicas > 0:
            await self._reconcile("scale_from_zero")

    async def scale_to(self, n: int, reason: str = "manual") -> None:
        """Explicit override (operators, tests, the SLO gate's epilogue)."""
        self.variant.desired_replicas = n
        await self._reconcile(reason)

    # ------------------------------------------------------------ reconcile
    async def _reconcile(self, reason: str) -> None:
        async with self._lock:
            desired = self.variant.desired_replicas
            current = len(self.replicas)
            if desired != current and self.families is not None:
                self.families.scale_decisions.labels(reason=reason).inc()
            if desired > current:
                await asyncio.gather(*(
                    self._launch_one(reason)
                    for _ in range(desired - current)))
            elif desired < current:
                for address in self._retire_candidates(current - desired):
                    await self._retire_one(address, reason)
            self.variant.current_replicas = len(self.replicas)
            self.variant.pending_replicas = 0

    async def _launch_one(self, reason: str) -> None:
        t0 = time.monotonic()
        try:
            handle = await self.launcher.launch()
        except Exception:
            return  # next tick retries; desired > current persists
        dt = time.monotonic() - t0
        kind = "warm" if handle.warm else "cold"
        self.launch_records.append(LaunchRecord(kind, dt, handle.address))
        if self.families is not None:
            self.families.warm_start.labels(kind=kind).observe(dt)
        self.replicas[handle.address] = handle
        from llmd_tpu.core.endpoint import EndpointRole

        role = getattr(handle, "role", None) or self.cfg.role
        self.pool.upsert(Endpoint(
            address=handle.address, name=handle.name,
            role=EndpointRole(role),
            labels={"llmd.ai/pool": self.cfg.model,
                    "llmd.ai/role": role}))
        if self.flight is not None:
            self.flight.record_system(
                "pool_warm_start", endpoint=handle.address, kind=kind,
                seconds=round(dt, 3))
            self.flight.record_system(
                "pool_scale_up", endpoint=handle.address, reason=reason,
                replicas=len(self.replicas))

    def _retire_candidates(self, n: int) -> list[str]:
        """Least-loaded first: retiring the busiest replica maximizes the
        drain wait and the KV state thrown away."""

        def load(address: str) -> float:
            ep = self.pool.get(address)
            if ep is None:
                return 0.0
            return (ep.metric(StdMetric.RUNNING_REQUESTS)
                    + ep.metric(StdMetric.QUEUED_REQUESTS))

        return sorted(self.replicas, key=load)[:n]

    async def _deregister(self, address: str) -> None:
        """Drop from discovery; the router's pool listener then evicts the
        breaker/poller state so churned replicas don't leak."""
        self.pool.remove(address)

    async def _retire_one(self, address: str, reason: str) -> None:
        handle = self.replicas.get(address)
        if handle is None:
            return
        if self.resilience is not None:  # stop new picks immediately
            self.resilience.set_draining(address, True)
        await self._drain(address)
        await self._deregister(address)
        del self.replicas[address]
        await self.launcher.stop(handle)
        if self.flight is not None:
            self.flight.record_system(
                "pool_scale_down", endpoint=address, reason=reason,
                replicas=len(self.replicas))

    async def _drain(self, address: str) -> None:
        if self._session is None:
            return
        import aiohttp

        try:
            await self._session.post(
                f"http://{address}/drain",
                params={"timeout_s": str(self.cfg.drain_timeout_s)},
                timeout=aiohttp.ClientTimeout(
                    total=self.cfg.drain_timeout_s + 2.0))
        except Exception:
            pass  # a dead replica can't drain; retire proceeds

    # ---------------------------------------------------------- self-healing
    async def _health_sweep(self) -> None:
        """Retire replicas whose /health stopped answering (killed, not
        drained). The reconcile that follows replaces them."""
        if self._session is None or not self.replicas:
            return
        import aiohttp

        async def probe(address: str) -> tuple[str, bool, str]:
            """Returns (address, healthy, detail). A 5xx body's structured
            reason (engine_stalled / fabric_dead from the device watchdog,
            obs/device.py) rides along so the retirement event says WHY the
            replica died, not just that it did."""
            try:
                async with self._session.get(
                    f"http://{address}/health",
                    timeout=aiohttp.ClientTimeout(
                        total=self.cfg.health_timeout_s),
                ) as r:
                    detail = ""
                    if r.status >= 500:
                        try:
                            body = await r.json()
                            detail = str(body.get("reason")
                                         or body.get("status") or "")
                        except Exception:
                            detail = ""
                    return address, r.status < 500, detail
            except Exception:
                return address, False, "unreachable"

        results = await asyncio.gather(*(probe(a) for a in list(self.replicas)))
        dead = [(a, detail) for a, ok, detail in results if not ok]
        if not dead:
            return
        async with self._lock:
            for address, detail in dead:
                handle = self.replicas.pop(address, None)
                if handle is None:
                    continue
                await self._deregister(address)
                try:
                    await self.launcher.kill(handle)
                except Exception:
                    pass
                if self.families is not None:
                    self.families.scale_decisions.labels(
                        reason="replica_dead").inc()
                if self.flight is not None:
                    self.flight.record_system(
                        "pool_scale_down", endpoint=address,
                        reason="replica_dead", detail=detail or "no_response",
                        replicas=len(self.replicas))
            self.variant.current_replicas = len(self.replicas)

    # ---------------------------------------------------------------- status
    def status(self) -> dict:
        return {
            "model": self.cfg.model,
            "desired_replicas": self.variant.desired_replicas,
            "ready_replicas": len(self.replicas),
            "replicas": sorted(self.replicas),
            "launches": [
                {"kind": r.kind, "seconds": round(r.seconds, 3),
                 "address": r.address}
                for r in self.launch_records],
        }
