"""Open-loop trace replay against the router, with SLO accounting.

The harness fires each :class:`~llmd_tpu.pool.traces.TraceRequest` at its
trace offset regardless of how the previous ones are doing (open loop — a
closed loop would self-throttle exactly when the pool is saturated and hide
the overload the autoscaler must react to). Thousands of concurrent streams
are just thousands of pending asyncio tasks on one session.

Per-request records capture status, e2e latency, and TTFT (streaming), and
:class:`ReplayReport` folds them into the gate verdict inputs: SLO
attainment, client-visible 5xx count, status histogram.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Optional

from llmd_tpu.pool.traces import TraceRequest


@dataclass
class RequestResult:
    tenant: str
    t_offset: float  # scheduled trace offset
    status: int  # HTTP status; -1 = transport error
    e2e_s: float
    ttft_s: Optional[float] = None  # streaming only
    error: str = ""

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300


@dataclass
class ReplayReport:
    """Everything tools/slo_check.py asserts on."""

    results: list[RequestResult] = field(default_factory=list)
    wall_s: float = 0.0
    slo_e2e_s: float = 0.0
    slo_ttft_s: Optional[float] = None

    @property
    def total(self) -> int:
        return len(self.results)

    @property
    def statuses(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for r in self.results:
            key = str(r.status)
            out[key] = out.get(key, 0) + 1
        return out

    @property
    def client_5xx(self) -> int:
        """Client-visible failures: 5xx responses AND transport errors."""
        return sum(1 for r in self.results if r.status >= 500 or r.status < 0)

    def _meets_slo(self, r: RequestResult) -> bool:
        if not r.ok or r.e2e_s > self.slo_e2e_s:
            return False
        if (self.slo_ttft_s is not None and r.ttft_s is not None
                and r.ttft_s > self.slo_ttft_s):
            return False
        return True

    @property
    def slo_attainment(self) -> float:
        """Fraction of ALL requests that succeeded within SLO — failures
        count against attainment, not just against goodput."""
        if not self.results:
            return 1.0
        return sum(1 for r in self.results if self._meets_slo(r)) / self.total

    def summary(self) -> dict:
        lat = sorted(r.e2e_s for r in self.results if r.ok)

        def pct(p: float) -> Optional[float]:
            if not lat:
                return None
            return round(lat[min(len(lat) - 1, int(len(lat) * p))], 4)

        return {
            "requests": self.total,
            "statuses": dict(sorted(self.statuses.items())),
            "client_5xx": self.client_5xx,
            "slo_e2e_s": self.slo_e2e_s,
            "slo_attainment": round(self.slo_attainment, 4),
            "p50_e2e_s": pct(0.50),
            "p99_e2e_s": pct(0.99),
            "wall_s": round(self.wall_s, 2),
        }


async def replay_trace(router_address: str, trace: list[TraceRequest],
                       model: str = "fake/model", slo_e2e_s: float = 3.0,
                       slo_ttft_s: Optional[float] = None,
                       time_scale: float = 1.0,
                       request_timeout_s: float = 30.0) -> ReplayReport:
    """Replay ``trace`` open-loop against ``http://<router_address>``.

    ``time_scale`` compresses/stretches offsets (0.5 = twice as fast).
    """
    import aiohttp

    report = ReplayReport(slo_e2e_s=slo_e2e_s, slo_ttft_s=slo_ttft_s)
    t0 = time.monotonic()

    async def one(req: TraceRequest, sess: aiohttp.ClientSession) -> None:
        delay = req.t * time_scale - (time.monotonic() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        body = {
            "model": model,
            "prompt": f"{req.tenant} " * max(1, req.prompt_tokens // 8),
            "max_tokens": req.max_tokens,
            "stream": req.stream,
        }
        sent = time.monotonic()
        ttft: Optional[float] = None
        try:
            async with sess.post(
                f"http://{router_address}/v1/completions", json=body,
                headers={"x-fairness-id": req.tenant},
                timeout=aiohttp.ClientTimeout(total=request_timeout_s),
            ) as resp:
                if req.stream and resp.status == 200:
                    async for _chunk in resp.content.iter_any():
                        if ttft is None:
                            ttft = time.monotonic() - sent
                else:
                    await resp.read()
                report.results.append(RequestResult(
                    tenant=req.tenant, t_offset=req.t, status=resp.status,
                    e2e_s=time.monotonic() - sent, ttft_s=ttft))
        except Exception as e:
            report.results.append(RequestResult(
                tenant=req.tenant, t_offset=req.t, status=-1,
                e2e_s=time.monotonic() - sent,
                error=type(e).__name__))

    # one connector sized for thousands of concurrent streams
    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as sess:
        await asyncio.gather(*(one(r, sess) for r in trace))
    report.wall_s = time.monotonic() - t0
    return report


def main() -> int:
    """CLI replay: ``python -m llmd_tpu.pool.harness --router host:port
    --trace trace.jsonl`` (or a built-in generator via ``--generate``)."""
    import argparse

    from llmd_tpu.pool import traces

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--router", required=True, help="router host:port")
    ap.add_argument("--trace", help="JSONL trace file (pool/traces.py format)")
    ap.add_argument("--generate", choices=["bursty", "diurnal", "ramp"],
                    help="generate a trace instead of loading one")
    ap.add_argument("--duration-s", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--model", default="fake/model")
    ap.add_argument("--slo-e2e-s", type=float, default=3.0)
    ap.add_argument("--time-scale", type=float, default=1.0)
    args = ap.parse_args()

    if args.trace:
        trace = traces.load_jsonl(args.trace)
    elif args.generate == "diurnal":
        trace = traces.diurnal_trace(duration_s=args.duration_s,
                                     seed=args.seed)
    elif args.generate == "ramp":
        trace = traces.multi_tenant_ramp(duration_s=args.duration_s,
                                         seed=args.seed)
    else:
        trace = traces.bursty_trace(duration_s=args.duration_s,
                                    seed=args.seed)
    report = asyncio.run(replay_trace(
        args.router, trace, model=args.model, slo_e2e_s=args.slo_e2e_s,
        time_scale=args.time_scale))
    print(json.dumps(report.summary(), indent=2))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
