"""Pool controller: replica lifecycle closing the autoscaling loop.

The autoscaling policies (``llmd_tpu/autoscaling/``) decide *how many*
replicas a pool should run; this package owns *making it so*:

- ``launcher``  — start/stop endpoint processes (in-process fakes for CI,
  ``engine/serve.py`` subprocesses on device) with snapshot-aware warm start;
- ``snapshot``  — engine-config-fingerprinted snapshot store so a 0→1
  transition skips the cold engine build;
- ``controller`` — the reconcile loop: live router metrics → WVA/HPA
  decision → launch/drain/retire, registering every replica with router
  discovery so the datalayer, scheduler, and breakers track the live set;
- ``traces``    — bursty / diurnal / multi-tenant ramp load generators;
- ``harness``   — open-loop trace replay against the router with SLO
  attainment accounting (tools/slo_check.py drives it in CI).
"""

from llmd_tpu.pool.controller import (
    PoolConfig,
    PoolController,
    replica_metrics_from_endpoint,
)
from llmd_tpu.pool.launcher import (
    FakeReplicaLauncher,
    ProcessReplicaLauncher,
    ReplicaHandle,
    ReplicaLauncher,
    engine_argv,
)
from llmd_tpu.pool.snapshot import PoolSnapshotStore, config_fingerprint

__all__ = [
    "FakeReplicaLauncher",
    "PoolConfig",
    "PoolController",
    "PoolSnapshotStore",
    "ProcessReplicaLauncher",
    "ReplicaHandle",
    "ReplicaLauncher",
    "config_fingerprint",
    "engine_argv",
    "replica_metrics_from_endpoint",
]
