"""Trace generators for the pool-scale load harness.

A trace is a list of :class:`TraceRequest` — arrival offsets plus request
shape — replayed open-loop by ``pool/harness.py``. Three generators cover
the autoscaling regimes the SLO gate exercises:

- :func:`bursty_trace`      — steady base rate with a 10x (configurable)
  burst window: the scale-up/scale-down swing;
- :func:`diurnal_trace`     — sinusoidal day/night rate: slow-follow
  tracking;
- :func:`multi_tenant_ramp` — per-tenant linear ramps with staggered
  starts: fairness under mixed growth.

Arrivals are inhomogeneous-Poisson (exponential gaps at the instantaneous
rate), seeded, so runs replay deterministically. Traces serialize to JSONL
(one request per line, keys = dataclass fields) for file-driven replays:

    {"t": 0.134, "tenant": "default", "prompt_tokens": 48, "max_tokens": 8,
     "stream": false}
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass
from typing import Callable, Optional


@dataclass
class TraceRequest:
    """One arrival: offset from trace start + request shape."""

    t: float  # seconds from trace start
    tenant: str = "default"
    prompt_tokens: int = 32
    max_tokens: int = 8
    stream: bool = False


def _poisson_arrivals(rate_fn: Callable[[float], float], duration_s: float,
                      rng: random.Random, tenant: str,
                      prompt_tokens: int, max_tokens: int,
                      stream: bool) -> list[TraceRequest]:
    """Inhomogeneous Poisson via thinning (Ogata): draw candidate gaps at the
    trace's peak rate, accept each with rate(t)/peak. Stepping gaps at the
    *instantaneous* rate would be wrong — one near-zero stretch (a tenant
    before its ramp onset) draws a gap past the whole trace."""
    peak = max(rate_fn(duration_s * k / 1000.0) for k in range(1001))
    peak = max(1e-6, peak)
    out: list[TraceRequest] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            return out
        if rng.random() * peak > rate_fn(t):
            continue  # thinned out: instantaneous rate below peak
        out.append(TraceRequest(
            t=round(t, 4), tenant=tenant,
            prompt_tokens=max(1, int(rng.gauss(prompt_tokens,
                                               prompt_tokens * 0.2))),
            max_tokens=max(1, int(rng.gauss(max_tokens, max_tokens * 0.2))),
            stream=stream))


def bursty_trace(duration_s: float = 10.0, base_rps: float = 5.0,
                 burst_rps: float = 50.0, burst_start_s: float = 4.0,
                 burst_end_s: float = 6.0, seed: int = 0,
                 prompt_tokens: int = 32, max_tokens: int = 8,
                 stream: bool = False) -> list[TraceRequest]:
    """Steady base rate with one rectangular burst window (default 10x)."""
    rng = random.Random(seed)

    def rate(t: float) -> float:
        return burst_rps if burst_start_s <= t < burst_end_s else base_rps

    return _poisson_arrivals(rate, duration_s, rng, "default",
                             prompt_tokens, max_tokens, stream)


def diurnal_trace(duration_s: float = 60.0, min_rps: float = 1.0,
                  peak_rps: float = 20.0, period_s: float = 30.0,
                  seed: int = 0, prompt_tokens: int = 32,
                  max_tokens: int = 8,
                  stream: bool = False) -> list[TraceRequest]:
    """Sinusoidal rate between min and peak (period = one 'day')."""
    import math

    rng = random.Random(seed)
    mid = (peak_rps + min_rps) / 2.0
    amp = (peak_rps - min_rps) / 2.0

    def rate(t: float) -> float:
        return mid + amp * math.sin(2.0 * math.pi * t / period_s)

    return _poisson_arrivals(rate, duration_s, rng, "default",
                             prompt_tokens, max_tokens, stream)


def multi_tenant_ramp(duration_s: float = 30.0,
                      tenants: Optional[list[str]] = None,
                      start_rps: float = 1.0, end_rps: float = 10.0,
                      stagger_s: float = 5.0, seed: int = 0,
                      prompt_tokens: int = 32, max_tokens: int = 8,
                      stream: bool = False) -> list[TraceRequest]:
    """Per-tenant linear ramps with staggered onsets, merged time-sorted."""
    tenants = tenants or ["tenant-a", "tenant-b", "tenant-c"]
    out: list[TraceRequest] = []
    for i, tenant in enumerate(tenants):
        rng = random.Random(seed + i)
        onset = i * stagger_s

        def rate(t: float, onset: float = onset) -> float:
            if t < onset:
                return 1e-6
            frac = (t - onset) / max(1e-6, duration_s - onset)
            return start_rps + (end_rps - start_rps) * min(1.0, frac)

        out.extend(_poisson_arrivals(rate, duration_s, rng, tenant,
                                     prompt_tokens, max_tokens, stream))
    out.sort(key=lambda r: r.t)
    return out


def dump_jsonl(trace: list[TraceRequest], path: str) -> None:
    with open(path, "w") as f:
        for req in trace:
            f.write(json.dumps(asdict(req)) + "\n")


def load_jsonl(path: str) -> list[TraceRequest]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(TraceRequest(**json.loads(line)))
    out.sort(key=lambda r: r.t)
    return out
