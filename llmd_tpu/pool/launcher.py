"""Replica launchers: the pool controller's process-lifecycle backends.

Two implementations of one contract:

- :class:`FakeReplicaLauncher` — in-process ``FakeModelServer`` replicas for
  CI and the SLO gate. A configurable ``engine_build_s`` sleep simulates the
  cold engine build; a snapshot hit (``PoolSnapshotStore``) skips it, which
  is exactly the warm-start contract the engine path honors for real.
- :class:`ProcessReplicaLauncher` — subprocess replicas (``testing/
  fake_server.py`` CLI or ``engine/serve.py`` via :func:`engine_argv`),
  readiness-gated on ``/health``.

``kill`` is deliberately part of the contract: chaos tooling
(tools/slo_check.py) needs to take a replica down *without* the drain
handshake, so the controller's health probe and the router's breakers — not
the launcher — have to notice.
"""

from __future__ import annotations

import asyncio
import copy
import os
import socket
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from llmd_tpu.pool.snapshot import PoolSnapshotStore, config_fingerprint


@dataclass
class ReplicaHandle:
    """One launched replica, as the controller tracks it."""

    address: str  # "host:port" the replica serves on
    name: str = ""
    warm: bool = False  # launched from a snapshot (skipped cold build)
    launched_at: float = field(default_factory=time.monotonic)
    server: Any = None  # in-process FakeModelServer (fake launcher)
    proc: Any = None  # subprocess.Popen (process launcher)
    role: str = "both"  # prefill | decode | both — copied onto the Endpoint
    sidecar: Any = None  # RoutingSidecar fronting a decode replica

    def __post_init__(self) -> None:
        if not self.name:
            self.name = self.address


class ReplicaLauncher:
    """Lifecycle contract the controller drives. All methods are async so
    process launchers can await readiness without blocking the loop."""

    async def launch(self) -> ReplicaHandle:
        raise NotImplementedError

    async def stop(self, handle: ReplicaHandle) -> None:
        """Graceful stop (the controller drains via the router first)."""
        raise NotImplementedError

    async def kill(self, handle: ReplicaHandle) -> None:
        """Abrupt stop: no drain, in-flight requests die. Chaos only."""
        await self.stop(handle)

    def alive(self, handle: ReplicaHandle) -> bool:
        raise NotImplementedError


class FakeReplicaLauncher(ReplicaLauncher):
    """In-process fake replicas with a simulated cold engine build.

    ``engine_config`` is fingerprinted exactly like the engine launcher's;
    the first launch pays ``engine_build_s`` and commits a snapshot, every
    later launch of the same config is warm (pays only ``restore_s``).
    """

    def __init__(self, server_config=None,
                 snapshots: Optional[PoolSnapshotStore] = None,
                 engine_config: Optional[dict] = None,
                 engine_build_s: float = 0.0,
                 restore_s: float = 0.0,
                 durable_store: bool = False,
                 role: str = "both",
                 with_sidecar: bool = False) -> None:
        from llmd_tpu.testing.fake_server import FakeServerConfig

        self.server_config = server_config or FakeServerConfig()
        self.snapshots = snapshots
        self.engine_config = engine_config if engine_config is not None else {
            "model": self.server_config.model,
            "block_size": self.server_config.block_size,
            "num_blocks": self.server_config.num_blocks,
        }
        self.engine_build_s = engine_build_s
        self.restore_s = restore_s
        # Durable prefix tier stand-in (docs/durable-tier.md): a graceful
        # stop — the controller only calls stop() after the drain handshake —
        # writes the replica's simulated block set back here, and a warm
        # launch restores it, so a 0→1 warm start recovers the prefix working
        # set, not just the compile cache. kill() deliberately skips the
        # write-back (no drain, no flush). Off by default: only opted-in
        # harnesses (tools/slo_check.py) should see restored prefixes.
        self.durable_store = durable_store
        self.durable_blocks: set[int] = set()
        # P/D disaggregation (docs/pd-disaggregation.md): role is stamped on
        # the replica config and the handle so the controller can label the
        # Endpoint; with_sidecar fronts decode replicas with a RoutingSidecar
        # that executes the x-prefiller-host-port split the router decides.
        self.role = role
        self.with_sidecar = with_sidecar
        self._seq = 0

    async def launch(self) -> ReplicaHandle:
        from llmd_tpu.testing.fake_server import FakeModelServer

        fp = config_fingerprint(self.engine_config)
        warm = self.snapshots is not None and self.snapshots.has(fp)
        if warm:
            if self.restore_s > 0:
                await asyncio.sleep(self.restore_s)
        else:
            if self.engine_build_s > 0:
                await asyncio.sleep(self.engine_build_s)  # simulated build
            if self.snapshots is not None:
                self.snapshots.save(fp, {"kind": "fake",
                                         "engine_config": self.engine_config})
        cfg = copy.deepcopy(self.server_config)
        if self.role != "both":
            cfg.role = self.role
        server = FakeModelServer(cfg)
        if self.durable_store and self.durable_blocks:
            # restore the written-back prefix working set into the simulated
            # paged cache: repeats hit these blocks, so prefill (∝ uncached
            # tokens) — and therefore TTFT — recovers along with the build
            now = time.monotonic()
            for h in self.durable_blocks:
                server.blocks[h] = now
        await server.start()
        self._seq += 1
        sidecar = None
        address = server.address
        if self.with_sidecar:
            from llmd_tpu.disagg.sidecar import RoutingSidecar

            sidecar = RoutingSidecar(decode_addr=server.address,
                                     prefill_timeout_s=2.0)
            await sidecar.start()
            address = sidecar.address  # traffic enters through the sidecar
        return ReplicaHandle(address=address,
                             name=f"fake-{self._seq}", warm=warm,
                             server=server, role=self.role, sidecar=sidecar)

    async def stop(self, handle: ReplicaHandle) -> None:
        if handle.sidecar is not None:
            sidecar, handle.sidecar = handle.sidecar, None
            await sidecar.stop()
        if handle.server is not None:
            if self.durable_store:
                # drain-time write-back: the controller drained before this
                self.durable_blocks.update(handle.server.blocks.keys())
            await handle.server.stop()
            handle.server = None

    async def kill(self, handle: ReplicaHandle) -> None:
        # aiohttp cleanup cancels in-flight handlers: clients see resets,
        # which is the abrupt-death signal the chaos gate wants. No durable
        # write-back: an abrupt death never ran the drain flush.
        if handle.sidecar is not None:
            sidecar, handle.sidecar = handle.sidecar, None
            await sidecar.stop()
        if handle.server is not None:
            server, handle.server = handle.server, None
            await server.stop()

    def alive(self, handle: ReplicaHandle) -> bool:
        return handle.server is not None and handle.server._runner is not None


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def fake_argv(port: int, *, model: str = "fake/model", block_size: int = 16,
              num_blocks: int = 512, max_running: int = 8,
              decode_us_per_token: float = 500.0,
              role: str = "both") -> list[str]:
    """argv for a subprocess FakeModelServer (testing/fake_server.py CLI)."""
    argv = [sys.executable, "-m", "llmd_tpu.testing.fake_server",
            "--port", str(port), "--model", model,
            "--block-size", str(block_size), "--num-blocks", str(num_blocks),
            "--max-running", str(max_running),
            "--decode-us-per-token", str(decode_us_per_token)]
    if role != "both":
        argv += ["--role", role]
    return argv


def engine_argv(model: str, port: int,
                snapshots: Optional[PoolSnapshotStore] = None,
                engine_config: Optional[dict] = None,
                extra: Optional[list[str]] = None) -> tuple[list[str], bool]:
    """argv for an ``engine/serve.py`` replica, warm-start aware.

    With a snapshot store, the materialized checkpoint and the persistent
    JAX compilation cache live under the config fingerprint: the first
    launch builds the checkpoint (testing/checkpoints.py for test models,
    a straight copy of HF dirs otherwise happens at serve time) and every
    relaunch reuses both — serve deserializes compiled programs instead of
    tracing them. Returns ``(argv, warm)``.
    """
    cfg = dict(engine_config or {})
    cfg.setdefault("model", model)
    argv = [sys.executable, "-m", "llmd_tpu.engine.serve",
            "--model", model, "--port", str(port)]
    warm = False
    if snapshots is not None:
        fp = config_fingerprint(cfg)
        warm = snapshots.has(fp)
        cache_dir = snapshots.path(fp, "compile_cache")
        if not os.path.isdir(model):  # test-model name → materialize once
            ckpt_dir = snapshots.path(fp, "checkpoint")
            if not os.path.exists(os.path.join(ckpt_dir, "config.json")):
                from llmd_tpu.testing.checkpoints import make_hf_checkpoint

                make_hf_checkpoint(ckpt_dir)
            argv[argv.index("--model") + 1] = ckpt_dir
        argv += ["--compile-cache-dir", cache_dir]
        if not warm:
            snapshots.save(fp, {"kind": "engine", "engine_config": cfg})
    argv += list(extra or [])
    return argv, warm


class ProcessReplicaLauncher(ReplicaLauncher):
    """Subprocess replicas readiness-gated on ``/health``.

    ``argv_fn(port) -> (argv, warm)`` (or ``argv`` alone, treated as cold)
    decouples the launcher from what it launches: ``fake_argv`` for CI,
    ``engine_argv`` for on-device pools.
    """

    def __init__(self, argv_fn: Callable[[int], Any], host: str = "127.0.0.1",
                 ready_timeout_s: float = 60.0,
                 env: Optional[dict[str, str]] = None) -> None:
        self.argv_fn = argv_fn
        self.host = host
        self.ready_timeout_s = ready_timeout_s
        self.env = env
        self._seq = 0

    async def launch(self) -> ReplicaHandle:
        import subprocess

        import aiohttp

        port = _free_port(self.host)
        built = self.argv_fn(port)
        argv, warm = built if isinstance(built, tuple) else (built, False)
        env = dict(os.environ, **(self.env or {}))
        proc = subprocess.Popen(argv, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        address = f"{self.host}:{port}"
        deadline = time.monotonic() + self.ready_timeout_s
        async with aiohttp.ClientSession() as sess:
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"replica process exited rc={proc.returncode} "
                        f"before becoming ready ({' '.join(argv[:4])}…)")
                try:
                    async with sess.get(
                        f"http://{address}/health",
                        timeout=aiohttp.ClientTimeout(total=1.0),
                    ) as r:
                        if r.status == 200:
                            self._seq += 1
                            return ReplicaHandle(address=address,
                                                 name=f"proc-{self._seq}",
                                                 warm=warm, proc=proc)
                except Exception:
                    pass
                await asyncio.sleep(0.05)
        proc.kill()
        raise TimeoutError(
            f"replica at {address} not ready within {self.ready_timeout_s}s")

    async def stop(self, handle: ReplicaHandle) -> None:
        if handle.proc is None:
            return
        handle.proc.terminate()
        try:
            await asyncio.to_thread(handle.proc.wait, 5.0)
        except Exception:
            handle.proc.kill()
        handle.proc = None

    async def kill(self, handle: ReplicaHandle) -> None:
        if handle.proc is not None:
            handle.proc.kill()
            await asyncio.to_thread(handle.proc.wait)
            handle.proc = None

    def alive(self, handle: ReplicaHandle) -> bool:
        return handle.proc is not None and handle.proc.poll() is None
