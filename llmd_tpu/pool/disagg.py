"""DisaggPoolSet: two role-labeled pools under one controller plane.

P/D disaggregation (docs/pd-disaggregation.md) splits a deployment into a
*prefill* pool and a *decode* pool with independent scaling laws:

- **prefill replicas** are sized by TTFT pressure — the router's flow-control
  queue depth is the fastest proxy for "prompts are waiting to be chunked",
  so the prefill controller defaults to the HPA policy fed the live flow
  depth (igw_queue_depth target) plus running totals;
- **decode replicas** are sized by KV residency and sustained tok/s — the
  decode controller defaults to the WVA saturation policy over per-replica
  ``kv_usage``/queue spare capacity, with the flow-depth input zeroed so a
  prompt backlog never inflates the decode pool (prefill owns that signal).

Both controllers share the *router's* EndpointPool: every replica lands in
discovery with ``role=prefill|decode`` threaded from the launcher handle
through :meth:`PoolController._launch_one`, which is what the scheduler's
``prefill-endpoints-filter`` / ``decode-endpoints-filter`` profiles key on —
live role attributes, not static config lists.

Each role reads its own env namespace (``LLMD_POOL_PREFILL_*`` /
``LLMD_POOL_DECODE_*``, deploy/ENV_VARS.md) and falls back to the shared
``LLMD_POOL_*`` defaults via :meth:`PoolConfig.from_env` overrides.

The per-role controllers get ``fleet = None``: the router-wide fleet rollup
sums *all* replicas' running requests, which would let decode load leak into
the prefill controller's HPA input (and vice versa); the per-replica
fallback in ``_running_total`` only sums the controller's own role.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from llmd_tpu.pool.controller import PoolConfig, PoolController, _env_f, _env_i
from llmd_tpu.pool.launcher import ReplicaLauncher


def prefill_pool_config(**overrides: Any) -> PoolConfig:
    """Prefill-pool knobs: LLMD_POOL_PREFILL_* over the shared defaults."""
    import os

    cfg = PoolConfig.from_env(role="prefill")
    cfg.min_replicas = _env_i("LLMD_POOL_PREFILL_MIN_REPLICAS",
                              cfg.min_replicas)
    cfg.max_replicas = _env_i("LLMD_POOL_PREFILL_MAX_REPLICAS",
                              cfg.max_replicas)
    cfg.interval_s = _env_f("LLMD_POOL_PREFILL_INTERVAL_S", cfg.interval_s)
    # queue-depth-driven by default: TTFT pressure shows up as flow backlog
    cfg.policy = os.environ.get("LLMD_POOL_PREFILL_POLICY", "hpa")
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def decode_pool_config(**overrides: Any) -> PoolConfig:
    """Decode-pool knobs: LLMD_POOL_DECODE_* over the shared defaults."""
    import os

    cfg = PoolConfig.from_env(role="decode")
    cfg.min_replicas = _env_i("LLMD_POOL_DECODE_MIN_REPLICAS",
                              cfg.min_replicas)
    cfg.max_replicas = _env_i("LLMD_POOL_DECODE_MAX_REPLICAS",
                              cfg.max_replicas)
    cfg.interval_s = _env_f("LLMD_POOL_DECODE_INTERVAL_S", cfg.interval_s)
    # KV-residency-driven by default: WVA saturation over kv spare capacity
    cfg.policy = os.environ.get("LLMD_POOL_DECODE_POLICY", "wva")
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


class DisaggPoolSet:
    """Two role-labeled PoolControllers over one shared router pool.

    ``prefill_launcher`` should hand out ``role="prefill"`` replicas and
    ``decode_launcher`` ``role="decode"`` ones (optionally sidecar-fronted);
    the set itself only wires signals and aggregates lifecycle/status.
    """

    def __init__(self, prefill_launcher: ReplicaLauncher,
                 decode_launcher: ReplicaLauncher,
                 router: Any = None,
                 prefill_cfg: Optional[PoolConfig] = None,
                 decode_cfg: Optional[PoolConfig] = None) -> None:
        pcfg = prefill_cfg if prefill_cfg is not None else \
            prefill_pool_config()
        dcfg = decode_cfg if decode_cfg is not None else decode_pool_config()
        pcfg.role, dcfg.role = "prefill", "decode"
        self.prefill = PoolController(
            pcfg, prefill_launcher, router=router,
            flow_depth_fn=self._prefill_queue_depth(router))
        # the HPA default target (8 queued) is sized for pools of large
        # replicas; prefill replicas admit ~2 concurrent chunked prefills,
        # so the TTFT-pressure target is its own knob (deploy/ENV_VARS.md)
        from llmd_tpu.autoscaling.hpa import ExternalMetric

        self.prefill.hpa.metrics = [
            ExternalMetric("igw_queue_depth",
                           target=_env_f("LLMD_POOL_PREFILL_QUEUE_TARGET",
                                         8.0),
                           target_type="Value"),
            ExternalMetric("igw_running_requests", target=16.0,
                           target_type="AverageValue"),
        ]
        # decode scaling must not see the prompt backlog: zero its flow input
        # so WVA reacts to per-replica KV residency / queue spare, not TTFT
        self.decode = PoolController(dcfg, decode_launcher, router=router,
                                     flow_depth_fn=lambda: 0.0)
        # router-wide rollups mix roles; force the per-replica fallback
        self.prefill.fleet = None
        self.decode.fleet = None

    @staticmethod
    def _prefill_queue_depth(router: Any):
        """TTFT-pressure signal for the prefill pool's HPA: the router's
        flow backlog (prompts not yet dispatched) plus outstanding prefill
        work on the prefill replicas themselves — queued behind the P
        pool's admission limit or already mid-prefill (a replica running
        at its admission limit is pressure, not steady state)."""
        from llmd_tpu.core.endpoint import EndpointRole
        from llmd_tpu.core.metrics_contract import StdMetric

        def depth() -> float:
            total = 0.0
            if router is not None and getattr(router, "flow", None) is not None:
                total += float(router.flow._total_queued())
            if router is not None:
                for ep in router.pool.list():
                    if ep.role == EndpointRole.PREFILL:
                        total += float(ep.metric(StdMetric.QUEUED_REQUESTS))
                        total += float(ep.metric(StdMetric.RUNNING_REQUESTS))
            return total

        return depth

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        await asyncio.gather(self.prefill.start(), self.decode.start())

    async def stop(self) -> None:
        await asyncio.gather(self.prefill.stop(), self.decode.stop())

    async def step(self) -> None:
        """One synchronous reconcile pass over both roles (tests/gates)."""
        await self.prefill.step()
        await self.decode.step()

    # ---------------------------------------------------------------- status
    def status(self) -> dict:
        return {"prefill": self.prefill.status(),
                "decode": self.decode.status()}
