"""Pool-level snapshot store: engine-config-fingerprinted warm-start state.

A cold 0→1 transition pays the full engine build (checkpoint materialize +
trace/compile + warmup — BENCH_r01 measured ~52s build + ~17s warmup on
device). Everything in that path is a pure function of the engine config,
so the pool controller snapshots the reusable artifacts once per config
fingerprint and later launches against the snapshot:

- fake mode: the snapshot's existence itself is the signal — the simulated
  engine-build delay is skipped;
- engine mode: the snapshot directory carries the materialized checkpoint
  and the persistent JAX compilation cache, handed to ``engine/serve.py``
  via ``--model`` / ``--compile-cache-dir`` so the relaunch deserializes
  compiled programs instead of rebuilding them.

Fingerprints are sha256 over the sorted-JSON engine config, mirroring how
the engine's own compile cache keys on (program shape, flags).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Optional


def config_fingerprint(config: dict[str, Any]) -> str:
    """Stable hash of an engine config dict (order-insensitive)."""
    blob = json.dumps(config, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class PoolSnapshotStore:
    """Filesystem store of per-fingerprint warm-start snapshots.

    Layout: ``<root>/<fingerprint>/meta.json`` plus whatever artifact
    directories the launcher parks next to it (``checkpoint/``,
    ``compile_cache/``). ``meta.json`` is written last, atomically, so a
    half-built snapshot never reads as warm.
    """

    def __init__(self, root_dir: str) -> None:
        self.root = root_dir
        os.makedirs(root_dir, exist_ok=True)

    def _dir(self, fingerprint: str) -> str:
        return os.path.join(self.root, fingerprint)

    def _meta_path(self, fingerprint: str) -> str:
        return os.path.join(self._dir(fingerprint), "meta.json")

    def has(self, fingerprint: str) -> bool:
        return os.path.exists(self._meta_path(fingerprint))

    def path(self, fingerprint: str, *parts: str) -> str:
        """Artifact path inside the snapshot dir (created on demand)."""
        d = os.path.join(self._dir(fingerprint), *parts)
        os.makedirs(d, exist_ok=True)
        return d

    def save(self, fingerprint: str, meta: dict[str, Any]) -> str:
        """Commit a snapshot: artifacts must already be in place under
        :meth:`path`; the atomic meta write flips it to warm."""
        os.makedirs(self._dir(fingerprint), exist_ok=True)
        payload = dict(meta)
        payload.setdefault("fingerprint", fingerprint)
        payload.setdefault("created_unix", round(time.time(), 3))
        tmp = self._meta_path(fingerprint) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
        os.replace(tmp, self._meta_path(fingerprint))
        return self._dir(fingerprint)

    def load(self, fingerprint: str) -> Optional[dict[str, Any]]:
        if not self.has(fingerprint):
            return None
        with open(self._meta_path(fingerprint)) as f:
            return json.load(f)

    def fingerprints(self) -> list[str]:
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.exists(self._meta_path(d)))
