"""Autoscaling plane: HPA/KEDA metrics path + Workload Variant Autoscaler.

Parity: reference docs/architecture/advanced/autoscaling/ — hpa-keda.md (external
metrics igw_queue_depth / igw_running_requests, dual-metric max, scale-to-zero) and
wva.md (variants, Analyzer→Optimizer→Enforcer pipeline, saturation-percentage and
saturation-token analyzers, Kalman/queueing SLO analyzer, scale-to/from-zero).
"""

from llmd_tpu.autoscaling.wva import (
    CostAwareOptimizer,
    Enforcer,
    GreedyByScoreOptimizer,
    KalmanTuner,
    PoolMetrics,
    ReplicaMetrics,
    SaturationAnalyzer,
    SLOAnalyzer,
    TokenSaturationAnalyzer,
    Variant,
    WVAEngine,
)
from llmd_tpu.autoscaling.hpa import HPAEvaluator, ExternalMetric

__all__ = [
    "CostAwareOptimizer",
    "Enforcer",
    "ExternalMetric",
    "GreedyByScoreOptimizer",
    "HPAEvaluator",
    "KalmanTuner",
    "PoolMetrics",
    "ReplicaMetrics",
    "SLOAnalyzer",
    "SaturationAnalyzer",
    "TokenSaturationAnalyzer",
    "Variant",
    "WVAEngine",
]
