"""HPA/KEDA external-metrics path over the EPP metrics.

Parity: reference hpa-keda.md:14-118 — Prometheus Adapter exposes two external
metrics from the EPP, HPA takes the max of both desired counts:

- ``igw_queue_depth`` (target type Value): pool-level queued requests; desired =
  ceil(current / target) — queue is a pool property, not per-replica,
- ``igw_running_requests`` (target type AverageValue): desired =
  ceil(current / (target × replicas)) scaled back to replicas.

The router already serves both series on /metrics; this evaluator reproduces the
HPA arithmetic so the policy is testable (and usable directly in no-k8s mode).
Scale-to-zero (0→1) is KEDA's job in the reference; here the WVA engine's
scale-from-zero loop covers it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional


@dataclass
class ExternalMetric:
    name: str
    target: float
    target_type: str = "Value"  # "Value" | "AverageValue"


class HPAEvaluator:
    """Dual-metric max rule (hpa-keda.md:64-90) with HPA's tolerance band."""

    def __init__(self, metrics: Optional[list[ExternalMetric]] = None,
                 min_replicas: int = 1, max_replicas: int = 10,
                 tolerance: float = 0.1) -> None:
        self.metrics = metrics or [
            ExternalMetric("igw_queue_depth", target=8.0, target_type="Value"),
            ExternalMetric("igw_running_requests", target=16.0, target_type="AverageValue"),
        ]
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.tolerance = tolerance

    def desired_replicas(self, current_replicas: int, values: dict[str, float]) -> int:
        desired = []
        for m in self.metrics:
            v = values.get(m.name)
            if v is None:
                continue
            if m.target_type == "AverageValue":
                ratio = v / (m.target * max(1, current_replicas))
            else:  # Value: pool-level quantity
                ratio = v / m.target
            if abs(ratio - 1.0) <= self.tolerance:
                desired.append(current_replicas)
            else:
                desired.append(math.ceil(ratio * max(1, current_replicas)))
        want = max(desired) if desired else current_replicas
        return min(self.max_replicas, max(self.min_replicas, want))
