"""Workload Variant Autoscaler: Analyzer → Optimizer → Enforcer pipeline.

Parity map into reference wva.md:
- variants & VA object (modelID group, cost, min/max replicas)      :5-11, :205-237
- pipeline stages                                                    :38-56
- saturation-percentage analyzer (kv≥0.80, queue≥5, spare 0.10/3,
  N/(N-1) scale-down simulation, transition blocking)                :58-76
- saturation-token analyzer (k1 memory / k2 compute chain
  observed→historical→derived→fallback, median across replicas,
  demand incl. EPP queue, thresholds up 0.85 / down 0.70)            :78-106
- SLO analyzer (Kalman-learned α/β/γ, explicit/inferred/fallback
  targets, M/M/1-style capacity, replicas = ⌈arrival/capacity⌉)      :108-125
- scale-to-zero (retention window) and 100ms scale-from-zero engine  :128-155

Kubernetes objects are abstracted: a ``Variant.scale`` callback plays the
Deployment/LWS reconcile role, so the same engine drives k8s or process groups
(no-Kubernetes mode).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------


@dataclass
class ReplicaMetrics:
    """Per-replica signals (wva.md 'Registered Queries': 1-minute windows)."""

    kv_usage: float = 0.0  # [0, 1]
    queue_len: float = 0.0
    num_blocks: int = 0  # KV capacity (blocks)
    block_size: int = 16
    tokens_in_use: float = 0.0  # resident KV tokens
    avg_in_tokens: float = 256.0
    avg_out_tokens: float = 128.0
    arrival_rate: float = 0.0  # req/s dispatched to this replica
    avg_ttft_s: float = 0.0
    avg_itl_s: float = 0.0


@dataclass
class PoolMetrics:
    """One InferencePool's snapshot: per-variant replica metrics + EPP queue."""

    replicas: dict[str, list[ReplicaMetrics]]  # variant name → ready replicas
    epp_queue_size: float = 0.0  # inference_extension_flow_control_queue_size
    requests_in_retention: float = 0.0  # scale-to-zero query


@dataclass
class Variant:
    """A VariantAutoscaling object (llmd.ai/v1alpha1, wva.md:205-237)."""

    name: str
    model_id: str
    cost: float = 10.0
    min_replicas: int = 1
    max_replicas: int = 2
    current_replicas: int = 1
    desired_replicas: int = 1
    pending_replicas: int = 0  # desired ahead of current (transitioning)
    scale: Optional[Callable[[int], None]] = None  # reconcile callback

    @property
    def transitioning(self) -> bool:
        return self.desired_replicas != self.current_replicas


@dataclass
class ScalingSignal:
    """Analyzer output: capacity needed / freeable, not a decision (wva.md:44-46)."""

    scale_up: int = 0  # replicas of capacity needed
    scale_down: int = 0  # replicas safely freeable
    priority: float = 0.0
    reason: str = ""


# ---------------------------------------------------------------------------
# Analyzers
# ---------------------------------------------------------------------------


class SaturationAnalyzer:
    """saturation-percentage-based (default, wva.md:60-76)."""

    def __init__(self, kv_threshold: float = 0.80, queue_threshold: float = 5.0,
                 kv_spare_trigger: float = 0.10, queue_spare_trigger: float = 3.0) -> None:
        self.kv_threshold = kv_threshold
        self.queue_threshold = queue_threshold
        self.kv_spare_trigger = kv_spare_trigger
        self.queue_spare_trigger = queue_spare_trigger

    def _saturated(self, r: ReplicaMetrics) -> bool:
        return r.kv_usage >= self.kv_threshold or r.queue_len >= self.queue_threshold

    def analyze(self, pool: PoolMetrics, variants: Sequence[Variant]) -> ScalingSignal:
        if any(v.transitioning for v in variants):
            return ScalingSignal(reason="blocked: variant transitioning")
        reps = [r for rs in pool.replicas.values() for r in rs]
        if not reps:
            return ScalingSignal(reason="no ready replicas")
        spare_kv = float(np.mean([max(0.0, self.kv_threshold - r.kv_usage) for r in reps]))
        spare_q = float(np.mean([max(0.0, self.queue_threshold - r.queue_len) for r in reps]))
        if spare_kv < self.kv_spare_trigger or spare_q < self.queue_spare_trigger:
            return ScalingSignal(scale_up=1, priority=1.0,
                                 reason=f"saturated (spare kv {spare_kv:.2f}, q {spare_q:.1f})")
        # scale-down: ≥2 non-saturated AND simulated N→N-1 redistribution keeps headroom
        healthy = [r for r in reps if not self._saturated(r)]
        n = len(reps)
        if len(healthy) >= 2 and n >= 2:
            factor = n / (n - 1)
            kv_after = [min(1.0, r.kv_usage * factor) for r in reps]
            q_after = [r.queue_len * factor for r in reps]
            spare_kv2 = float(np.mean([max(0.0, self.kv_threshold - u) for u in kv_after]))
            spare_q2 = float(np.mean([max(0.0, self.queue_threshold - q) for q in q_after]))
            if spare_kv2 >= self.kv_spare_trigger and spare_q2 >= self.queue_spare_trigger:
                return ScalingSignal(scale_down=1, reason="spare capacity after N/(N-1) sim")
        return ScalingSignal(reason="steady")


class TokenSaturationAnalyzer:
    """saturation-token-based (experimental, wva.md:78-106): absolute token
    capacity vs demand with the k1/k2 dual-bound model."""

    HISTORY_WINDOW = 10

    def __init__(self, kv_threshold: float = 0.80, queue_threshold: float = 5.0,
                 scale_up_threshold: float = 0.85, scale_down_boundary: float = 0.70,
                 max_batched_tokens: Optional[int] = None) -> None:
        self.kv_threshold = kv_threshold
        self.queue_threshold = queue_threshold
        self.up = scale_up_threshold
        self.down = scale_down_boundary
        self.max_batched_tokens = max_batched_tokens
        self._k2_history: dict[str, deque[float]] = {}  # bucket → observations
        self.capacity_cache: dict[str, float] = {}  # variant → tokens (zero-replica est.)

    @staticmethod
    def _bucket(r: ReplicaMetrics) -> str:
        """Output-length workload bucketing for compute-bound history (wva.md:104)."""
        if r.avg_out_tokens < 100:
            return "short"
        if r.avg_out_tokens < 500:
            return "medium"
        return "long"

    def _k2(self, r: ReplicaMetrics, k1: float) -> float:
        """compute-bound chain: observed → historical → derived → fallback=k1."""
        hist = self._k2_history.setdefault(self._bucket(r), deque(maxlen=self.HISTORY_WINDOW))
        if r.queue_len >= self.queue_threshold and r.tokens_in_use > 0:
            hist.append(r.tokens_in_use)  # observed at saturation
            return r.tokens_in_use
        if hist:
            return float(np.mean(hist))
        if self.max_batched_tokens:  # derived from deployment args (steady-state model)
            total = r.avg_in_tokens + r.avg_out_tokens
            return self.max_batched_tokens * (total / max(1.0, r.avg_out_tokens))
        return k1

    def replica_capacity(self, r: ReplicaMetrics) -> float:
        k1 = r.num_blocks * r.block_size * self.kv_threshold
        return min(k1, self._k2(r, k1))

    def analyze(self, pool: PoolMetrics, variants: Sequence[Variant]) -> ScalingSignal:
        reps = [r for rs in pool.replicas.values() for r in rs]
        if not reps:
            return ScalingSignal(scale_up=1 if pool.epp_queue_size > 0 else 0,
                                 reason="no ready replicas")
        per_variant_cap: dict[str, float] = {}
        for vname, rs in pool.replicas.items():
            if rs:
                per_variant_cap[vname] = float(np.median([self.replica_capacity(r) for r in rs]))
                self.capacity_cache[vname] = per_variant_cap[vname]
        supply = sum(per_variant_cap.get(v, 0.0) * len(rs)
                     for v, rs in pool.replicas.items())
        demand = sum(r.tokens_in_use + r.queue_len * r.avg_in_tokens for r in reps)
        avg_in = float(np.mean([r.avg_in_tokens for r in reps]))
        demand += pool.epp_queue_size * avg_in  # EPP queue rides on pool demand
        required = demand / self.up - supply
        spare = supply - demand / self.down
        med_cap = float(np.median(list(per_variant_cap.values()))) if per_variant_cap else 1.0
        if required > 0:
            return ScalingSignal(scale_up=max(1, math.ceil(required / max(1.0, med_cap))),
                                 priority=required, reason=f"demand {demand:.0f} > supply {supply:.0f}")
        if spare > med_cap:  # a whole replica's worth of slack
            return ScalingSignal(scale_down=1, reason=f"spare {spare:.0f} tokens")
        return ScalingSignal(reason="steady")


class KalmanTuner:
    """Online learning of (α, β, γ) — baseline overhead, per-token compute,
    per-token KV access (wva.md:110-117) — via a linear Kalman filter.

    Observation model (documented simplification of the reference's):
      TTFT ≈ α + β·in_tokens                (prefill pass over the prompt)
      ITL  ≈ α + β + γ·(in_tokens + out/2)  (one decode step + KV read of context)
    """

    def __init__(self, q: float = 1e-7, r: float = 1e-3) -> None:
        self.x = np.array([0.01, 1e-4, 1e-5])  # [alpha_s, beta_s/token, gamma_s/token]
        self.P = np.eye(3) * 1.0
        self.Q = np.eye(3) * q
        self.R = r
        self.updates = 0

    def update(self, m: ReplicaMetrics) -> None:
        obs = []
        if m.avg_ttft_s > 0:
            obs.append((np.array([1.0, m.avg_in_tokens, 0.0]), m.avg_ttft_s))
        if m.avg_itl_s > 0:
            ctx = m.avg_in_tokens + m.avg_out_tokens / 2.0
            obs.append((np.array([1.0, 1.0, ctx]), m.avg_itl_s))
        for H, z in obs:
            self.P = self.P + self.Q
            S = float(H @ self.P @ H) + self.R
            K = (self.P @ H) / S
            self.x = self.x + K * (z - float(H @ self.x))
            self.x = np.maximum(self.x, 0.0)  # physical parameters are nonnegative
            self.P = (np.eye(3) - np.outer(K, H)) @ self.P
            self.updates += 1

    @property
    def alpha(self) -> float:
        return float(self.x[0])

    @property
    def beta(self) -> float:
        return float(self.x[1])

    @property
    def gamma(self) -> float:
        return float(self.x[2])

    def idle_ttft(self, in_tokens: float) -> float:
        return self.alpha + self.beta * in_tokens

    def idle_itl(self, in_tokens: float, out_tokens: float) -> float:
        return self.alpha + self.beta + self.gamma * (in_tokens + out_tokens / 2.0)


class SLOAnalyzer:
    """Queueing-model analyzer (wva.md:108-125): replicas = ⌈arrival rate /
    max sustainable rate within SLO⌉, with M/M/1-style waiting."""

    def __init__(self, target_ttft_s: Optional[float] = None,
                 target_itl_s: Optional[float] = None, slo_multiplier: float = 3.0) -> None:
        self.tuner = KalmanTuner()
        self.target_ttft = target_ttft_s  # explicit targets (ConfigMap path)
        self.target_itl = target_itl_s
        self.k = slo_multiplier  # inferred mode: target = idle latency × k

    def _targets(self, m: ReplicaMetrics) -> tuple[float, float]:
        if self.target_ttft is not None and self.target_itl is not None:
            return self.target_ttft, self.target_itl
        if self.tuner.updates >= 8:  # inferred (default): idle-latency multiplier
            return (self.k * max(1e-4, self.tuner.idle_ttft(m.avg_in_tokens)),
                    self.k * max(1e-5, self.tuner.idle_itl(m.avg_in_tokens, m.avg_out_tokens)))
        # fallback: observed × 1.5 headroom (capped)
        return (min(30.0, 1.5 * max(m.avg_ttft_s, 1e-3)),
                min(1.0, 1.5 * max(m.avg_itl_s, 1e-4)))

    def max_rate_per_replica(self, m: ReplicaMetrics) -> float:
        """Largest arrival rate (req/s) for which M/M/1 response time ≤ target.

        Service time s = idle e2e (TTFT + out·ITL); response time 1/(μ−λ) ≤ T
        ⇒ λ_max = μ − 1/T.
        """
        t_ttft, t_itl = self._targets(m)
        s = max(1e-3, self.tuner.idle_ttft(m.avg_in_tokens)
                + m.avg_out_tokens * self.tuner.idle_itl(m.avg_in_tokens, m.avg_out_tokens))
        target = max(s * 1.01, t_ttft + m.avg_out_tokens * t_itl)
        mu = 1.0 / s
        return max(0.01, mu - 1.0 / target)

    def analyze(self, pool: PoolMetrics, variants: Sequence[Variant]) -> ScalingSignal:
        reps = [r for rs in pool.replicas.values() for r in rs]
        if not reps:
            return ScalingSignal(scale_up=1 if pool.epp_queue_size > 0 else 0,
                                 reason="no ready replicas")
        for r in reps:
            self.tuner.update(r)
        total_rate = sum(r.arrival_rate for r in reps)
        cap = float(np.mean([self.max_rate_per_replica(r) for r in reps]))
        desired = max(1, math.ceil(total_rate / max(1e-6, cap)))
        current = len(reps)
        if desired > current:
            return ScalingSignal(scale_up=desired - current, priority=desired - current,
                                 reason=f"rate {total_rate:.2f}/s needs {desired} replicas")
        if desired < current - 0:  # hysteresis: only free whole surplus replicas
            return ScalingSignal(scale_down=current - desired,
                                 reason=f"rate {total_rate:.2f}/s needs only {desired}")
        return ScalingSignal(reason="steady")


# ---------------------------------------------------------------------------
# Optimizers + Enforcer
# ---------------------------------------------------------------------------


class CostAwareOptimizer:
    """Default unlimited mode (wva.md:48-50): scale up the cheapest variant with
    headroom, scale down the most expensive with replicas."""

    def decide(self, signal: ScalingSignal, variants: list[Variant]) -> None:
        if signal.scale_up > 0:
            remaining = signal.scale_up
            for v in sorted(variants, key=lambda v: v.cost):
                if remaining <= 0:
                    break
                if v.pending_replicas > 0:  # skip variants with pending replicas
                    continue
                room = v.max_replicas - v.desired_replicas
                add = min(room, remaining)
                if add > 0:
                    v.desired_replicas += add
                    remaining -= add
        elif signal.scale_down > 0:
            remaining = signal.scale_down
            for v in sorted(variants, key=lambda v: -v.cost):
                if remaining <= 0:
                    break
                drop = min(v.desired_replicas - v.min_replicas, remaining)
                if drop > 0:
                    v.desired_replicas -= drop
                    remaining -= drop


class GreedyByScoreOptimizer:
    """Limited mode (enableLimiter, wva.md:50): fair-share a global accelerator
    budget across pools by priority score."""

    def __init__(self, total_accelerators: int) -> None:
        self.total = total_accelerators

    def decide_all(self, signals: dict[str, ScalingSignal],
                   pools: dict[str, list[Variant]]) -> None:
        budget = self.total - sum(
            v.desired_replicas for vs in pools.values() for v in vs
        )
        # grant scale-ups in priority order while budget lasts
        for model_id in sorted(signals, key=lambda m: -signals[m].priority):
            sig = signals[model_id]
            if sig.scale_up <= 0:
                continue
            grant = min(sig.scale_up, max(0, budget))
            if grant > 0:
                CostAwareOptimizer().decide(
                    ScalingSignal(scale_up=grant), pools[model_id]
                )
                budget -= grant
        for model_id, sig in signals.items():
            if sig.scale_down > 0:
                CostAwareOptimizer().decide(sig, pools[model_id])


class Enforcer:
    """Post-optimization policies (wva.md:52-56, 128-141): scale-to-zero after an
    idle retention window, else ensure ≥1 replica on the cheapest variant."""

    def __init__(self, scale_to_zero: bool = False, retention_s: float = 600.0) -> None:
        self.scale_to_zero = scale_to_zero
        self.retention_s = retention_s

    def enforce(self, pool: PoolMetrics, variants: list[Variant]) -> None:
        if self.scale_to_zero and all(v.min_replicas == 0 for v in variants):
            if pool.requests_in_retention == 0:
                for v in variants:
                    v.desired_replicas = 0
                return
        if all(v.desired_replicas == 0 for v in variants) and not self.scale_to_zero:
            cheapest = min(variants, key=lambda v: v.cost)
            cheapest.desired_replicas = 1
        for v in variants:
            v.desired_replicas = min(max(v.desired_replicas, v.min_replicas
                                         if not self.scale_to_zero else 0),
                                     v.max_replicas)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class WVAEngine:
    """The background scaling engine: slow analyze loop + 100ms scale-from-zero.

    ``metrics_fn(model_id) -> PoolMetrics`` abstracts Prometheus/pod scraping;
    ``Variant.scale`` abstracts the controller reconcile.
    """

    def __init__(
        self,
        pools: dict[str, list[Variant]],
        metrics_fn: Callable[[str], PoolMetrics],
        analyzer=None,
        optimizer=None,
        enforcer: Optional[Enforcer] = None,
        interval_s: float = 30.0,
        scale_from_zero_interval_s: float = 0.1,
    ) -> None:
        self.pools = pools
        self.metrics_fn = metrics_fn
        self.analyzer = analyzer or SaturationAnalyzer()
        self.optimizer = optimizer or CostAwareOptimizer()
        self.enforcer = enforcer or Enforcer()
        self.interval = interval_s
        self.sfz_interval = scale_from_zero_interval_s
        self.decisions: list[tuple[str, str, int]] = []  # (model, variant, replicas)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # one full pipeline pass over every pool (the 30s loop body)
    def step(self) -> dict[str, ScalingSignal]:
        signals: dict[str, ScalingSignal] = {}
        for model_id, variants in self.pools.items():
            pool = self.metrics_fn(model_id)
            sig = self.analyzer.analyze(pool, variants)
            signals[model_id] = sig
            if isinstance(self.optimizer, GreedyByScoreOptimizer):
                continue  # decided globally below
            self.optimizer.decide(sig, variants)
            self.enforcer.enforce(pool, variants)
            self._reconcile(model_id, variants)
        if isinstance(self.optimizer, GreedyByScoreOptimizer):
            self.optimizer.decide_all(signals, self.pools)
            for model_id, variants in self.pools.items():
                self.enforcer.enforce(self.metrics_fn(model_id), variants)
                self._reconcile(model_id, variants)
        return signals

    def scale_from_zero_step(self) -> None:
        """Fast path (wva.md:143-155): idle pool + queued EPP requests → 1 replica."""
        for model_id, variants in self.pools.items():
            if any(v.current_replicas > 0 or v.desired_replicas > 0 for v in variants):
                continue
            pool = self.metrics_fn(model_id)
            if pool.epp_queue_size > 0:
                cheapest = min(variants, key=lambda v: v.cost)
                cheapest.desired_replicas = 1
                self._reconcile(model_id, variants)

    def _reconcile(self, model_id: str, variants: list[Variant]) -> None:
        for v in variants:
            if v.desired_replicas != v.current_replicas:
                self.decisions.append((model_id, v.name, v.desired_replicas))
                if v.scale is not None:
                    v.scale(v.desired_replicas)
                v.pending_replicas = max(0, v.desired_replicas - v.current_replicas)

    # -- background loops --------------------------------------------------
    def start(self) -> None:
        t1 = threading.Thread(target=self._loop, daemon=True, name="wva-engine")
        t2 = threading.Thread(target=self._sfz_loop, daemon=True, name="wva-sfz")
        t1.start()
        t2.start()
        self._threads = [t1, t2]

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.step()
            except Exception:
                pass

    def _sfz_loop(self) -> None:
        while not self._stop.wait(self.sfz_interval):
            try:
                self.scale_from_zero_step()
            except Exception:
                pass
