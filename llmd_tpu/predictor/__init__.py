"""Latency predictor: online-trained TTFT/TPOT models + EPP plugins.

Parity: reference docs/architecture/advanced/latency-predictor.md — training sidecar
(sliding window, stratified bucketing) + prediction sidecars sharing a model volume
(:20-57), feature sets (:76-97), EPP plugin suite (:108-140), heuristic fallback on
outage (:52), actual-vs-predicted observability (:142-160). XGBoost → sklearn
HistGradientBoosting (same GBDT family; the image carries no xgboost).
"""

from llmd_tpu.predictor.model import (
    LatencyModel,
    LatencySample,
    StratifiedWindow,
    ttft_features,
    tpot_features,
)
from llmd_tpu.predictor.client import LocalPredictor, SidecarPredictorClient

__all__ = [
    "LatencyModel",
    "LatencySample",
    "StratifiedWindow",
    "LocalPredictor",
    "SidecarPredictorClient",
    "ttft_features",
    "tpot_features",
]
