"""TTFT/TPOT regression models + the stratified sliding training window.

Reference latency-predictor.md:70-97: two GBDT regressors retrained on a sliding
window of completed requests; stratified bucketing partitions samples by KV-cache
utilization (10% steps) and prefix-hit rate (0.25 steps) with a per-bucket cap so
rare regimes survive in the window; ~5% MAPE is the reference's accuracy bar.
"""

from __future__ import annotations

import pickle
import threading
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

# TTFT features (latency-predictor.md:78-87)
TTFT_FEATURES = (
    "kv_usage", "input_len", "queue_depth", "running_requests",
    "prefix_match_pct", "inflight_tokens",
)
# TPOT features (:89-97)
TPOT_FEATURES = (
    "kv_usage", "input_len", "queue_depth", "running_requests", "tokens_generated",
)


@dataclass
class LatencySample:
    """One completed request's pod-state features + observed latencies."""

    kv_usage: float = 0.0  # [0, 1]
    input_len: float = 0.0
    queue_depth: float = 0.0
    running_requests: float = 0.0
    prefix_match_pct: float = 0.0  # [0, 1]
    inflight_tokens: float = 0.0
    tokens_generated: float = 0.0
    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None

    def features(self, names: tuple[str, ...]) -> list[float]:
        return [float(getattr(self, n)) for n in names]


def ttft_features(sample: LatencySample) -> list[float]:
    return sample.features(TTFT_FEATURES)


def tpot_features(sample: LatencySample) -> list[float]:
    return sample.features(TPOT_FEATURES)


class StratifiedWindow:
    """Sliding window bucketed by (kv-util decile, prefix-hit quartile).

    Each bucket is its own bounded deque, so a regime that is rare in current
    traffic (cold cache at low load) keeps its samples while hot regimes churn
    theirs (latency-predictor.md:74).
    """

    def __init__(self, per_bucket_cap: int = 256) -> None:
        self.cap = per_bucket_cap
        self.buckets: dict[tuple[int, int], deque[LatencySample]] = {}
        self._lock = threading.Lock()

    @staticmethod
    def bucket_key(s: LatencySample) -> tuple[int, int]:
        kv = min(9, int(s.kv_usage * 10))  # 10% steps
        ph = min(3, int(s.prefix_match_pct * 4))  # 0.25 steps
        return (kv, ph)

    def add(self, sample: LatencySample) -> None:
        key = self.bucket_key(sample)
        with self._lock:
            dq = self.buckets.get(key)
            if dq is None:
                dq = self.buckets[key] = deque(maxlen=self.cap)
            dq.append(sample)

    def snapshot(self) -> list[LatencySample]:
        with self._lock:
            return [s for dq in self.buckets.values() for s in dq]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(dq) for dq in self.buckets.values())


class LatencyModel:
    """The two regressors (TTFT, TPOT) + fit/predict/serialize.

    sklearn HistGradientBoostingRegressor plays XGBoost's role; with <min_samples
    the model is unfit and callers fall back to the composite heuristic.
    """

    MIN_SAMPLES = 32

    def __init__(self) -> None:
        self.ttft = None
        self.tpot = None
        self.version = 0
        self.train_count = 0
        self.mape = {"ttft": None, "tpot": None}  # on the training window (holdout tail)

    # ------------------------------------------------------------------ train
    def fit(self, samples: list[LatencySample]) -> bool:
        from sklearn.ensemble import HistGradientBoostingRegressor

        ttft_rows = [(ttft_features(s), s.ttft_ms) for s in samples if s.ttft_ms is not None]
        tpot_rows = [(tpot_features(s), s.tpot_ms) for s in samples if s.tpot_ms is not None]
        fitted = False
        for name, rows in (("ttft", ttft_rows), ("tpot", tpot_rows)):
            if len(rows) < self.MIN_SAMPLES:
                continue
            X = np.asarray([r[0] for r in rows], np.float64)
            y = np.asarray([r[1] for r in rows], np.float64)
            n_hold = max(1, len(rows) // 10)
            model = HistGradientBoostingRegressor(
                max_iter=100, max_depth=6, learning_rate=0.1, min_samples_leaf=4,
            )
            model.fit(X[:-n_hold] if len(rows) > n_hold else X,
                      y[:-n_hold] if len(rows) > n_hold else y)
            pred = model.predict(X[-n_hold:])
            denom = np.maximum(np.abs(y[-n_hold:]), 1e-6)
            self.mape[name] = float(np.mean(np.abs(pred - y[-n_hold:]) / denom))
            setattr(self, name, model)
            fitted = True
        if fitted:
            self.version += 1
            self.train_count += 1
        return fitted

    # ---------------------------------------------------------------- predict
    def is_fit(self) -> bool:
        return self.ttft is not None

    def predict(self, samples: list[LatencySample]) -> list[tuple[Optional[float], Optional[float]]]:
        """Per sample: (predicted ttft_ms, predicted tpot_ms); None when unfit."""
        if not samples:
            return []
        out_t: list[Optional[float]] = [None] * len(samples)
        out_p: list[Optional[float]] = [None] * len(samples)
        if self.ttft is not None:
            X = np.asarray([ttft_features(s) for s in samples], np.float64)
            out_t = [max(0.0, float(v)) for v in self.ttft.predict(X)]
        if self.tpot is not None:
            X = np.asarray([tpot_features(s) for s in samples], np.float64)
            out_p = [max(0.0, float(v)) for v in self.tpot.predict(X)]
        return list(zip(out_t, out_p))

    # -------------------------------------------------------------- serialize
    def save(self, path: str | Path) -> None:
        """Atomic write to the shared model volume (training→prediction handoff)."""
        path = Path(path)
        tmp = path.with_suffix(f".tmp{self.version}")
        with open(tmp, "wb") as f:
            pickle.dump({"ttft": self.ttft, "tpot": self.tpot,
                         "version": self.version, "mape": self.mape}, f)
        tmp.replace(path)

    @classmethod
    def load(cls, path: str | Path) -> "LatencyModel":
        with open(path, "rb") as f:
            d = pickle.load(f)
        m = cls()
        m.ttft, m.tpot = d["ttft"], d["tpot"]
        m.version, m.mape = d["version"], d.get("mape", m.mape)
        return m


def heuristic_latency(sample: LatencySample) -> tuple[float, float]:
    """Composite fallback when the predictor is unavailable
    (latency-predictor.md:52): a fixed-form estimate from KV utilization, queue
    depth, and prefix match — units are pseudo-ms, only the ordering matters."""
    uncached = sample.input_len * (1.0 - sample.prefix_match_pct)
    ttft = (
        0.2 * uncached
        + 50.0 * sample.queue_depth
        + 200.0 * max(0.0, sample.kv_usage - 0.8)
        + 0.02 * sample.inflight_tokens
    )
    tpot = 5.0 + 2.0 * sample.running_requests + 100.0 * max(0.0, sample.kv_usage - 0.9)
    return ttft, tpot
