"""Predictor access for the EPP producer: in-process or sidecar HTTP.

Two modes (both satisfy the same interface the plugins consume):

- ``LocalPredictor`` — model + window live in the router process (standalone /
  no-Kubernetes mode; zero hot-path RPC). Retraining runs on a background thread.
- ``SidecarPredictorClient`` — blocking HTTP to the prediction sidecars with a tight
  timeout and round-robin over replicas; samples go to the training sidecar
  fire-and-forget. Failure → None, and callers fall back to the composite heuristic
  (latency-predictor.md:52).
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.request
from typing import Optional, Sequence

from llmd_tpu.predictor.model import (
    LatencyModel,
    LatencySample,
    StratifiedWindow,
    heuristic_latency,
)


class LocalPredictor:
    """In-process train+predict (the single-binary deployment shape)."""

    def __init__(self, retrain_interval_s: float = 5.0, per_bucket_cap: int = 256) -> None:
        self.window = StratifiedWindow(per_bucket_cap)
        self.model = LatencyModel()
        self.retrain_interval = retrain_interval_s
        self._lock = threading.Lock()
        self._last_fit = 0.0

    def predict(self, samples: Sequence[LatencySample]) -> Optional[list[tuple[float, float]]]:
        with self._lock:
            if not self.model.is_fit():
                return None
            preds = self.model.predict(list(samples))
        return [(t if t is not None else heuristic_latency(s)[0],
                 p if p is not None else heuristic_latency(s)[1])
                for (t, p), s in zip(preds, samples)]

    def record(self, sample: LatencySample) -> None:
        self.window.add(sample)
        now = time.monotonic()
        if now - self._last_fit >= self.retrain_interval:
            self._last_fit = now
            threading.Thread(target=self._fit, daemon=True).start()

    def _fit(self) -> None:
        samples = self.window.snapshot()
        if not samples:
            return
        model = LatencyModel()
        with self._lock:
            model.version = self.model.version
        if model.fit(samples):
            with self._lock:
                model.train_count = self.model.train_count + 1
                self.model = model

    def fit_now(self) -> bool:
        """Synchronous refit (tests/calibration)."""
        samples = self.window.snapshot()
        if not samples:
            return False
        with self._lock:
            return self.model.fit(samples)


class SidecarPredictorClient:
    """Talks to prediction/training sidecars (latency-predictor.md deployment)."""

    def __init__(self, predict_urls: Sequence[str], train_url: Optional[str] = None,
                 timeout_s: float = 0.15) -> None:
        self.predict_urls = list(predict_urls)
        self.train_url = train_url
        self.timeout_s = timeout_s
        self.failures = 0

    def _post(self, url: str, payload: dict, timeout: float) -> Optional[dict]:
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())
        except Exception:
            return None

    def predict(self, samples: Sequence[LatencySample]) -> Optional[list[tuple[float, float]]]:
        if not self.predict_urls or not samples:
            return None
        urls = self.predict_urls
        start = random.randrange(len(urls))
        for i in range(len(urls)):  # round-robin with failover
            url = urls[(start + i) % len(urls)]
            out = self._post(f"{url}/predict", {
                "samples": [s.__dict__ for s in samples]
            }, self.timeout_s)
            if out and out.get("predictions"):
                return [
                    (d["ttft_ms"] if d["ttft_ms"] is not None else heuristic_latency(s)[0],
                     d["tpot_ms"] if d["tpot_ms"] is not None else heuristic_latency(s)[1])
                    for d, s in zip(out["predictions"], samples)
                ]
        self.failures += 1
        return None

    def record(self, sample: LatencySample) -> None:
        if self.train_url is None:
            return
        threading.Thread(  # fire-and-forget; training is off the hot path
            target=self._post,
            args=(f"{self.train_url}/samples", {"samples": [sample.__dict__]}, 2.0),
            daemon=True,
        ).start()
