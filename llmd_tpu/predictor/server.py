"""Latency-predictor sidecars: training server + prediction server.

Deployment shape per reference latency-predictor.md:22-57: both run next to the EPP;
the training server ingests completed-request samples and periodically refits,
writing the model to a shared volume; N prediction servers watch that file and answer
the EPP's hot-path /predict calls (scale-out table :99-107).

API:
  training server:   POST /samples {"samples": [{...feature/latency fields...}]}
                     GET  /model/info
  prediction server: POST /predict {"samples": [{...feature fields...}]}
                     → {"predictions": [{"ttft_ms": x|null, "tpot_ms": y|null}]}
  both:              GET /health, GET /metrics
"""

from __future__ import annotations

import asyncio
import os
import time
from pathlib import Path
from typing import Optional

from aiohttp import web

from llmd_tpu.predictor.model import LatencyModel, LatencySample, StratifiedWindow

_SAMPLE_FIELDS = (
    "kv_usage", "input_len", "queue_depth", "running_requests",
    "prefix_match_pct", "inflight_tokens", "tokens_generated", "ttft_ms", "tpot_ms",
)


def sample_from_dict(d: dict) -> LatencySample:
    return LatencySample(**{k: d[k] for k in _SAMPLE_FIELDS if d.get(k) is not None})


class TrainingServer:
    """Ingests samples into the stratified window; refits on an interval."""

    def __init__(self, model_path: str, host: str = "127.0.0.1", port: int = 0,
                 retrain_interval_s: float = 5.0, per_bucket_cap: int = 256) -> None:
        self.model_path = model_path
        self.host, self.port = host, port
        self.retrain_interval = retrain_interval_s
        self.window = StratifiedWindow(per_bucket_cap)
        self.model = LatencyModel()
        self.samples_total = 0
        self._runner: Optional[web.AppRunner] = None
        self._task: Optional[asyncio.Task] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/samples", self._samples)
        app.router.add_get("/model/info", self._info)
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self._task = asyncio.get_running_loop().create_task(self._retrain_loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._runner:
            await self._runner.cleanup()

    async def retrain_now(self) -> bool:
        """One fit cycle (also used by tests to avoid sleeping out the interval)."""
        samples = self.window.snapshot()
        if not samples:
            return False
        loop = asyncio.get_running_loop()
        fitted = await loop.run_in_executor(None, self.model.fit, samples)
        if fitted:
            await loop.run_in_executor(None, self.model.save, self.model_path)
        return fitted

    async def _retrain_loop(self) -> None:
        while True:
            await asyncio.sleep(self.retrain_interval)
            try:
                await self.retrain_now()
            except Exception:
                pass  # a bad fit cycle must not kill ingestion

    async def _samples(self, request: web.Request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)
        rows = body.get("samples", [])
        for d in rows:
            self.window.add(sample_from_dict(d))
        self.samples_total += len(rows)
        return web.json_response({"accepted": len(rows), "window": len(self.window)})

    async def _info(self, request: web.Request):
        return web.json_response({
            "version": self.model.version, "train_count": self.model.train_count,
            "mape": self.model.mape, "window": len(self.window),
        })

    async def _health(self, request: web.Request):
        return web.json_response({"status": "ok"})

    async def _metrics(self, request: web.Request):
        lines = [
            f"llmd_tpu:predictor_samples_total {self.samples_total}",
            f"llmd_tpu:predictor_window_size {len(self.window)}",
            f"llmd_tpu:predictor_model_version {self.model.version}",
        ]
        for k, v in self.model.mape.items():
            if v is not None:
                lines.append(f'llmd_tpu:predictor_mape{{target="{k}"}} {v:.6f}')
        return web.Response(text="\n".join(lines) + "\n")


class PredictionServer:
    """Serves /predict from the newest model on the shared volume (mtime watch)."""

    def __init__(self, model_path: str, host: str = "127.0.0.1", port: int = 0,
                 reload_interval_s: float = 2.0) -> None:
        self.model_path = model_path
        self.host, self.port = host, port
        self.reload_interval = reload_interval_s
        self.model: Optional[LatencyModel] = None
        self._mtime = 0.0
        self._last_check = 0.0
        self.predictions_total = 0
        self._runner: Optional[web.AppRunner] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _maybe_reload(self) -> None:
        now = time.monotonic()
        if now - self._last_check < self.reload_interval and self.model is not None:
            return
        self._last_check = now
        try:
            mtime = os.path.getmtime(self.model_path)
        except OSError:
            return
        if mtime > self._mtime:
            try:
                self.model = LatencyModel.load(self.model_path)
                self._mtime = mtime
            except Exception:
                pass  # half-written file (save is atomic, but be defensive)

    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/predict", self._predict)
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _predict(self, request: web.Request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)
        self._maybe_reload()
        samples = [sample_from_dict(d) for d in body.get("samples", [])]
        self.predictions_total += len(samples)
        if self.model is None or not self.model.is_fit():
            return web.json_response({"predictions": None, "reason": "model not ready"},
                                     status=503)
        preds = self.model.predict(samples)
        return web.json_response({"predictions": [
            {"ttft_ms": t, "tpot_ms": p} for t, p in preds
        ]})

    async def _health(self, request: web.Request):
        ok = self.model is not None
        return web.json_response({"status": "ok" if ok else "no model"},
                                 status=200 if ok else 503)

    async def _metrics(self, request: web.Request):
        v = self.model.version if self.model else 0
        return web.Response(text=(
            f"llmd_tpu:predictor_predictions_total {self.predictions_total}\n"
            f"llmd_tpu:predictor_loaded_model_version {v}\n"
        ))
