"""Native (C++) runtime components, built on demand and loaded via ctypes.

The reference ships its data-plane libraries as C++ (NIXL, UCX, NVSHMEM — SURVEY.md
§2.5); ours are compiled from csrc/ with the toolchain baked into the image (g++).
No pybind11 in the image → plain C ABI + ctypes. Every native component has a Python
fallback so the framework degrades gracefully where a compiler is unavailable.
"""

from llmd_tpu.native.build import load_library, native_available

__all__ = ["load_library", "native_available"]
