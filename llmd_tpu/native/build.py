"""On-demand g++ build + ctypes loader for csrc/ libraries, with result caching.

Build artifacts land in ``csrc/.build/<name>-<source_hash>.so`` so rebuilds happen
only when the source changes; concurrent builders race benignly (atomic rename).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

_CSRC = Path(__file__).resolve().parent.parent.parent / "csrc"
_CACHE: dict[str, Optional[ctypes.CDLL]] = {}


def _build(name: str) -> Optional[Path]:
    src = _CSRC / f"{name}.cpp"
    if not src.exists():
        return None
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    build_dir = _CSRC / ".build"
    out = build_dir / f"{name}-{digest}.so"
    if out.exists():
        return out
    build_dir.mkdir(exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=build_dir)
    os.close(fd)
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-Wall", "-Werror", str(src), "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Compile (if needed) and dlopen csrc/<name>.cpp; None if unbuildable."""
    if name in _CACHE:
        return _CACHE[name]
    path = _build(name)
    lib = None
    if path is not None:
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            lib = None
    _CACHE[name] = lib
    return lib


def native_available(name: str) -> bool:
    return load_library(name) is not None
