"""LRU cache for compiled token grammars.

Compilation (schema -> regex -> char DFA -> token lift over the vocab) is
the expensive step — milliseconds for choices, potentially seconds for large
HF vocabs — while agentic traffic reuses a handful of schemas across
thousands of requests. Keys hash the *derived regex* (so textually different
bodies that lower identically share an entry) plus a tokenizer fingerprint
(a grammar lifted over one vocab is meaningless for another).

Capacity comes from ``LLMD_STRUCTURED_CACHE_SIZE`` (default 64), read when
the process-global cache is first touched.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

from llmd_tpu.structured.grammar import TokenGrammar

DEFAULT_CACHE_SIZE = 64


def _env_capacity() -> int:
    try:
        return max(1, int(os.environ.get("LLMD_STRUCTURED_CACHE_SIZE",
                                         str(DEFAULT_CACHE_SIZE))))
    except ValueError:
        return DEFAULT_CACHE_SIZE


class GrammarCache:
    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity if capacity is not None else _env_capacity()
        self._entries: OrderedDict[tuple, TokenGrammar] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get_or_compile(self, key: tuple,
                       builder: Callable[[], TokenGrammar]) -> tuple[TokenGrammar, bool]:
        """(grammar, was_hit). The build runs outside the lock: a concurrent
        miss on the same key compiles twice rather than serializing every
        request behind one compile."""
        with self._lock:
            grammar = self._entries.get(key)
            if grammar is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return grammar, True
        grammar = builder()
        with self._lock:
            self._entries[key] = grammar
            self._entries.move_to_end(key)
            self.misses += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return grammar, False


_global: Optional[GrammarCache] = None
_global_lock = threading.Lock()


def global_cache() -> GrammarCache:
    global _global
    with _global_lock:
        if _global is None:
            _global = GrammarCache()
        return _global


def reset_global_cache() -> None:
    """Drop the process-global cache (tests re-read the env on next use)."""
    global _global
    with _global_lock:
        _global = None
