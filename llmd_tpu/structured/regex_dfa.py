"""Character-level regex -> DFA compiler for constrained decoding.

Self-contained (the zero-egress image has no outlines/xgrammar): a small
regex dialect is parsed to an AST, lowered to a Thompson epsilon-NFA whose
edges carry character *sets*, then determinized by subset construction and
trimmed to live states (states from which an accepting state is reachable).
The result is the char-level automaton `grammar.TokenGrammar` lifts to the
tokenizer vocabulary (Willard & Louf 2023, "Efficient Guided Generation").

Supported syntax: literals, `.`, escapes (`\\d \\D \\w \\W \\s \\S \\n \\t
\\r` + escaped literal), classes `[a-z0-9_]` / negated `[^...]`, groups
`(...)` (and non-capturing `(?:...)`), alternation `|`, quantifiers `* + ?
{m} {m,} {m,n}`, and anchors `^`/`$` (no-ops: matching is always
full-string). Everything is defined over a finite printable-ASCII universe,
which keeps `.`, negated classes, and `\\D/\\W/\\S` finite — tokens
containing characters outside the universe simply can never be allowed,
which is the correct degradation for a constrainer (it restricts, never
widens).
"""

from __future__ import annotations

from dataclasses import dataclass

# Finite character universe (printable ASCII + \t \n \r). `.`, negated
# classes, and complement escapes expand over exactly this set.
UNIVERSE: frozenset[str] = (frozenset(chr(c) for c in range(32, 127))
                            | frozenset("\t\n\r"))

_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r")

# Bounded-repetition expansion is literal copies; cap it so a hostile
# {1,100000} cannot DoS the compiler (requests hit this as a 400).
MAX_REPEAT = 256
# Subset construction is worst-case exponential; a hostile pattern must fail
# compilation (-> 400), not stall the serving process.
MAX_DFA_STATES = 20000


class RegexError(ValueError):
    """Unsupported or malformed pattern (maps to HTTP 400 at the servers)."""


def escape_literal(text: str) -> str:
    """Escape ``text`` so it matches itself under this dialect."""
    return "".join("\\" + ch if ch in "\\.^$*+?()[]{}|" else ch
                   for ch in text)


# ------------------------------------------------------------------ AST
# nodes: ("lit", frozenset[str]) | ("cat", [nodes]) | ("alt", [nodes])
#        | ("rep", node, lo, hi|None)

_ESCAPES = {
    "d": _DIGITS, "D": UNIVERSE - _DIGITS,
    "w": _WORD, "W": UNIVERSE - _WORD,
    "s": _SPACE, "S": UNIVERSE - _SPACE,
}
_ESCAPE_LITERALS = {"n": "\n", "t": "\t", "r": "\r"}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def _next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise RegexError(f"unexpected {self.p[self.i]!r} at {self.i}")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self._next()
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        parts = []
        while self._peek() not in (None, "|", ")"):
            parts.append(self._repeat())
        if len(parts) == 1:
            return parts[0]
        return ("cat", parts)

    def _repeat(self):
        node = self._atom()
        while True:
            ch = self._peek()
            if ch == "*":
                self._next()
                node = ("rep", node, 0, None)
            elif ch == "+":
                self._next()
                node = ("rep", node, 1, None)
            elif ch == "?":
                self._next()
                node = ("rep", node, 0, 1)
            elif ch == "{":
                node = ("rep", node, *self._braces())
            else:
                return node

    def _braces(self) -> tuple[int, int | None]:
        start = self.i
        self._next()  # "{"
        body = ""
        while self._peek() not in (None, "}"):
            body += self._next()
        if self._peek() is None:
            raise RegexError(f"unterminated {{...}} at {start}")
        self._next()  # "}"
        try:
            if "," not in body:
                lo = hi = int(body)
            else:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s else 0
                hi = int(hi_s) if hi_s else None
        except ValueError:
            raise RegexError(f"bad repetition {{{body}}}") from None
        if hi is not None and (hi < lo or hi > MAX_REPEAT):
            raise RegexError(f"bad repetition bounds {{{body}}} "
                             f"(max {MAX_REPEAT})")
        if lo > MAX_REPEAT:
            raise RegexError(f"repetition too large {{{body}}}")
        return lo, hi

    def _atom(self):
        ch = self._next()
        if ch == "(":
            if self.p[self.i:self.i + 2] == "?:":
                self.i += 2
            elif self._peek() == "?":
                raise RegexError(f"unsupported group (?{self.p[self.i + 1:self.i + 2]}...)")
            node = self._alt()
            if self._peek() != ")":
                raise RegexError("unbalanced (")
            self._next()
            return node
        if ch == "[":
            return ("lit", self._cls())
        if ch == "\\":
            return ("lit", self._escape())
        if ch == ".":
            return ("lit", UNIVERSE)
        if ch in "^$":
            return ("cat", [])  # anchors are no-ops under full matching
        if ch in "*+?{":
            raise RegexError(f"nothing to repeat before {ch!r}")
        if ch in ")|":
            raise RegexError(f"unexpected {ch!r}")
        return ("lit", frozenset((ch,)))

    def _escape(self) -> frozenset[str]:
        if self._peek() is None:
            raise RegexError("dangling backslash")
        ch = self._next()
        if ch in _ESCAPES:
            return _ESCAPES[ch]
        return frozenset((_ESCAPE_LITERALS.get(ch, ch),))

    def _cls(self) -> frozenset[str]:
        negate = False
        if self._peek() == "^":
            self._next()
            negate = True
        chars: set[str] = set()
        first = True
        while True:
            ch = self._peek()
            if ch is None:
                raise RegexError("unterminated [...]")
            if ch == "]" and not first:
                self._next()
                break
            first = False
            self._next()
            if ch == "\\":
                chars |= self._escape()
                continue
            # range a-z (a lone trailing "-" is a literal)
            if self._peek() == "-" and self.p[self.i + 1:self.i + 2] not in ("", "]"):
                self._next()
                hi = self._next()
                if hi == "\\":
                    hi = next(iter(self._escape()))
                if ord(hi) < ord(ch):
                    raise RegexError(f"bad range {ch}-{hi}")
                chars |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
            else:
                chars.add(ch)
        return frozenset(UNIVERSE - chars) if negate else frozenset(chars)


# ------------------------------------------------------------ NFA -> DFA


class _NFA:
    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset[str], int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node) -> tuple[int, int]:
        kind = node[0]
        if kind == "lit":
            s, e = self.state(), self.state()
            if node[1]:
                self.edges[s].append((node[1], e))
            else:  # empty class matches nothing: s has no out-edges
                pass
            return s, e
        if kind == "cat":
            s = cur = self.state()
            for child in node[1]:
                cs, ce = self.build(child)
                self.eps[cur].append(cs)
                cur = ce
            return s, cur
        if kind == "alt":
            s, e = self.state(), self.state()
            for child in node[1]:
                cs, ce = self.build(child)
                self.eps[s].append(cs)
                self.eps[ce].append(e)
            return s, e
        if kind == "rep":
            _, child, lo, hi = node
            s = cur = self.state()
            for _ in range(lo):
                cs, ce = self.build(child)
                self.eps[cur].append(cs)
                cur = ce
            if hi is None:  # star/plus tail: loop
                cs, ce = self.build(child)
                e = self.state()
                self.eps[cur] += [cs, e]
                self.eps[ce] += [cs, e]
                return s, e
            # bounded optional copies, each skippable to the end
            e = self.state()
            for _ in range(hi - lo):
                cs, ce = self.build(child)
                self.eps[cur] += [cs, e]
                cur = ce
            self.eps[cur].append(e)
            return s, e
        raise AssertionError(f"unknown node {kind}")


def _closure(states: set[int], eps: list[list[int]]) -> frozenset[int]:
    seen = set(states)
    stack = list(states)
    while stack:
        for nxt in eps[stack.pop()]:
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return frozenset(seen)


@dataclass(frozen=True)
class CharDFA:
    """Trimmed char-level DFA: every state can still reach acceptance."""

    start: int
    accept: frozenset[int]
    trans: tuple[dict[str, int], ...]

    @property
    def n_states(self) -> int:
        return len(self.trans)


def compile_regex(pattern: str) -> CharDFA:
    """Full-match DFA for ``pattern``; raises RegexError on unsupported or
    unsatisfiable (matches-nothing) patterns."""
    nfa = _NFA()
    start, end = nfa.build(_Parser(pattern).parse())

    start_set = _closure({start}, nfa.eps)
    ids: dict[frozenset[int], int] = {start_set: 0}
    trans: list[dict[str, int]] = [{}]
    accept: set[int] = set()
    queue = [start_set]
    while queue:
        cur = queue.pop()
        cid = ids[cur]
        if end in cur:
            accept.add(cid)
        moves: dict[str, set[int]] = {}
        for ns in cur:
            for chars, tgt in nfa.edges[ns]:
                for ch in chars:
                    moves.setdefault(ch, set()).add(tgt)
        for ch, tgts in moves.items():
            nxt = _closure(tgts, nfa.eps)
            if nxt not in ids:
                if len(ids) >= MAX_DFA_STATES:
                    raise RegexError(
                        f"pattern too complex (> {MAX_DFA_STATES} DFA states)")
                ids[nxt] = len(ids)
                trans.append({})
                queue.append(nxt)
            trans[cid][ch] = ids[nxt]

    # trim to live states (can reach an accepting state)
    rev: list[set[int]] = [set() for _ in trans]
    for sid, edges in enumerate(trans):
        for tgt in edges.values():
            rev[tgt].add(sid)
    live = set(accept)
    stack = list(accept)
    while stack:
        for src in rev[stack.pop()]:
            if src not in live:
                live.add(src)
                stack.append(src)
    if 0 not in live:
        raise RegexError("pattern matches no strings")
    remap = {old: new for new, old in enumerate(sorted(live))}
    new_trans = tuple(
        {ch: remap[t] for ch, t in trans[old].items() if t in live}
        for old in sorted(live))
    return CharDFA(start=remap[0],
                   accept=frozenset(remap[a] for a in accept),
                   trans=new_trans)
