"""Token-level grammar: the char DFA lifted onto the tokenizer vocabulary.

For every live char-DFA state and every vocab token, the token's decoded
string is walked through the char automaton; tokens whose walk survives into
a live state become the state's allowed set, and the (state, token) -> state
map is the decoding-time automaton. This is the precompute that makes the
per-step cost a single array scatter: ``fill_bias`` writes 0 at allowed ids
and a large negative bias everywhere else, and the packed ``[rows, V]`` bias
is added to the logits on device before argmax/sample.

EOS is grammar-external: it is allowed exactly at accepting states (the
constrained text is complete) and moves the automaton to a synthetic
terminal state where only further EOS is allowed — so ``ignore_eos``
benchmarks keep a well-defined mask instead of counting violations.
"""

from __future__ import annotations

import numpy as np

from llmd_tpu.structured.regex_dfa import UNIVERSE, CharDFA

# Additive ban bias. Finite (not -inf) so a fully-banned top-k tail softmaxes
# to ~0 instead of NaN; at float32 it dominates any real logit by ~7 orders.
NEG_BIAS = np.float32(-1e9)


def token_strings(tokenizer, vocab_size: int) -> dict[int, str]:
    """id -> decoded string for every maskable vocab entry. Specials and
    tokens containing out-of-universe characters are omitted (they can never
    satisfy a grammar, so omission == ban, the safe direction)."""
    out: dict[int, str] = {}
    special = {getattr(tokenizer, "bos_id", -1), getattr(tokenizer, "eos_id", -1)}
    for tid in range(min(tokenizer.vocab_size, vocab_size)):
        if tid in special:
            continue
        try:
            text = tokenizer.decode([tid])
        except Exception:
            continue
        if text and all(ch in UNIVERSE for ch in text):
            out[tid] = text
    return out


class TokenGrammar:
    """Immutable compiled artifact shared across requests via the LRU cache."""

    def __init__(self, dfa: CharDFA, tok_strs: dict[int, str], eos_id: int,
                 vocab_size: int):
        n = dfa.n_states
        self.eos_id = eos_id
        self.vocab_size = vocab_size
        self.start = dfa.start
        self.accept = dfa.accept
        self.terminal = n  # synthetic post-EOS state
        self.n_states = n + 1
        nxt: list[dict[int, int]] = [{} for _ in range(n)]
        for tid, text in tok_strs.items():
            # walk once per (state, token); prefix-sharing tries would speed
            # large HF vocabs but the compile is LRU-cached either way
            for s in range(n):
                st: int | None = s
                for ch in text:
                    st = dfa.trans[st].get(ch)  # type: ignore[index]
                    if st is None:
                        break
                if st is not None:
                    nxt[s][tid] = st
        self._next = nxt
        allowed: list[np.ndarray] = []
        for s in range(n):
            ids = sorted(nxt[s])
            if s in dfa.accept:
                ids.append(eos_id)
            if not ids:
                # no token can extend this live state (vocab gap): force
                # finish rather than livelock; _retire counts the truncation
                ids = [eos_id]
            allowed.append(np.asarray(ids, np.int32))
        allowed.append(np.asarray([eos_id], np.int32))  # terminal
        self._allowed = allowed

    def advance(self, state: int, tid: int) -> int | None:
        """Next state after emitting ``tid``, or None if it violates."""
        if state == self.terminal:
            return self.terminal if tid == self.eos_id else None
        if tid == self.eos_id:
            return self.terminal if state in self.accept else None
        return self._next[state].get(tid)

    def legal_prefix_len(self, state: int, tokens) -> int:
        """Length of the longest prefix of ``tokens`` that stays inside the
        grammar when consumed from ``state`` — FSM-aware draft truncation:
        the speculative drafter keeps the legal prefix of an n-gram
        continuation instead of skipping constrained rows outright."""
        n = 0
        for t in tokens:
            nxt = self.advance(state, t)
            if nxt is None:
                break
            state = nxt
            n += 1
        return n

    def allowed_ids(self, state: int) -> np.ndarray:
        return self._allowed[state]

    def is_complete(self, state: int) -> bool:
        """The constrained text parses fully at this state."""
        return state == self.terminal or state in self.accept

    def fill_bias(self, row: np.ndarray, state: int) -> None:
        """Write the additive mask for ``state`` into a ``[V]`` f32 row."""
        row.fill(NEG_BIAS)
        row[self._allowed[state]] = 0.0

    def dense_tables(self) -> tuple[np.ndarray, np.ndarray]:
        """Whole-automaton dense form for the device-resident decode chain:
        ``bias [S, V] f32`` (rows exactly as ``fill_bias`` would write them)
        and ``nxt [S, V] i32`` (state transition per emitted token).

        A violating token self-loops in ``nxt`` — the device freezes on the
        same state the host-side ``StructuredState.sync`` freeze lands on, so
        replaying the emitted tokens through ``advance`` reproduces the
        device's trajectory bit-for-bit (that replay is still how violations
        get counted). Cached on the grammar, which is itself LRU-cached.
        """
        cached = getattr(self, "_dense", None)
        if cached is not None:
            return cached
        S, V = self.n_states, self.vocab_size
        bias = np.full((S, V), NEG_BIAS, np.float32)
        nxt = np.tile(np.arange(S, dtype=np.int32)[:, None], (1, V))
        for s in range(S):
            bias[s, self._allowed[s]] = 0.0
        for s, row in enumerate(self._next):
            for tid, st in row.items():
                nxt[s, tid] = st
            if s in self.accept:
                nxt[s, self.eos_id] = self.terminal
        self._dense = (bias, nxt)
        return self._dense


class StructuredState:
    """Per-sequence automaton cursor.

    The cursor is (state, n_seen) over ``token_ids[prompt_len:]`` and is
    re-derived lazily from the sequence's own token history — preemption
    resets KV/progress but never generated tokens, so ``sync`` after
    re-prefill lands on exactly the pre-preemption state with no extra
    bookkeeping in the preemption path.
    """

    __slots__ = ("grammar", "kind", "state", "n_seen", "violations",
                 "mask_logged")

    def __init__(self, grammar: TokenGrammar, kind: str):
        self.grammar = grammar
        self.kind = kind
        self.state = grammar.start
        self.n_seen = 0
        self.violations = 0
        self.mask_logged = False

    def sync(self, token_ids: list[int], prompt_len: int) -> int:
        """Advance over tokens appended since the last sync; returns how many
        violated the grammar (state freezes at the first violation)."""
        gen = token_ids[prompt_len:]
        if self.n_seen > len(gen):  # defensive: token history never shrinks
            self.state, self.n_seen = self.grammar.start, 0
        fresh_violations = 0
        for tid in gen[self.n_seen:]:
            nxt = self.grammar.advance(self.state, tid)
            if nxt is None:
                fresh_violations += 1
            else:
                self.state = nxt
            self.n_seen += 1
        self.violations += fresh_violations
        return fresh_violations

    @property
    def complete(self) -> bool:
        return self.grammar.is_complete(self.state)
