"""Structured outputs: grammar-constrained decoding with on-device masks.

Pipeline: an OpenAI-shaped request (``guided_choice`` / ``guided_regex`` /
``response_format`` json_object|json_schema) lowers to a regex
(`json_schema.py`), compiles to a char-level DFA (`regex_dfa.py`), lifts to
a token-level automaton over the real tokenizer vocab (`grammar.py`), and is
shared across requests through an LRU keyed by regex hash + tokenizer
fingerprint (`cache.py`). At each step the engine extracts the current
state's allow-set into a packed ``[rows, V]`` additive bias the sampler adds
on device — logits never leave the accelerator, and engines that never see
a structured request never compile the biased sampler (lazy jit, mirroring
``spec.py``).

Validation is split to fail fast: ``validate_structured_body`` needs no
tokenizer (router + engine frontend reject malformed bodies as 400 before
flow control/admission); ``compile_grammar`` does the vocab lift engine-side.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional

from llmd_tpu.structured.cache import (
    GrammarCache,
    global_cache,
    reset_global_cache,
)
from llmd_tpu.structured.grammar import (
    NEG_BIAS,
    StructuredState,
    TokenGrammar,
    token_strings,
)
from llmd_tpu.structured.json_schema import (
    json_object_regex,
    regex_for_schema,
    validate_instance,
)
from llmd_tpu.structured.regex_dfa import (
    RegexError,
    compile_regex,
    escape_literal,
)

__all__ = [
    "GrammarCache", "NEG_BIAS", "RegexError", "StructuredState",
    "TokenGrammar", "compile_grammar", "compile_regex", "escape_literal",
    "global_cache", "json_object_regex", "parse_logit_bias",
    "regex_for_schema", "reset_global_cache", "spec_to_regex",
    "structured_spec", "token_strings", "validate_instance",
    "validate_structured_body",
]


def structured_spec(sampling) -> Optional[tuple[str, Any]]:
    """(kind, payload) a SamplingParams constrains to, or None. Precedence
    follows vLLM: explicit guided_* beats response_format."""
    if getattr(sampling, "guided_choice", None):
        return ("choice", list(sampling.guided_choice))
    if getattr(sampling, "guided_regex", None):
        return ("regex", sampling.guided_regex)
    rf = getattr(sampling, "response_format", None)
    if isinstance(rf, dict):
        typ = rf.get("type")
        if typ == "json_object":
            return ("json_object", None)
        if typ == "json_schema":
            return ("json_schema", (rf.get("json_schema") or {}).get("schema"))
    return None


def spec_to_regex(kind: str, payload) -> str:
    if kind == "choice":
        if not payload or not all(isinstance(c, str) and c for c in payload):
            raise ValueError("guided_choice must be a non-empty list of "
                             "non-empty strings")
        return "(" + "|".join(escape_literal(c) for c in payload) + ")"
    if kind == "regex":
        if not isinstance(payload, str) or not payload:
            raise ValueError("guided_regex must be a non-empty string")
        return payload
    if kind == "json_object":
        return json_object_regex()
    if kind == "json_schema":
        if not isinstance(payload, dict):
            raise ValueError("response_format.json_schema.schema must be an "
                             "object")
        return regex_for_schema(payload)
    raise ValueError(f"unknown structured kind {kind!r}")


def parse_logit_bias(raw) -> Optional[dict[int, float]]:
    """OpenAI ``logit_bias``: {token_id: bias in [-100, 100]} with string or
    int keys. Returns a normalized {int: float} map (None when absent/empty);
    raises ValueError on malformed input."""
    if raw is None:
        return None
    if not isinstance(raw, dict):
        raise ValueError("logit_bias must be an object of token_id -> bias")
    out: dict[int, float] = {}
    for key, val in raw.items():
        try:
            tid = int(key)
            bias = float(val)
        except (TypeError, ValueError):
            raise ValueError(
                f"logit_bias entry {key!r}: {val!r} is not token_id -> "
                f"number") from None
        if tid < 0:
            raise ValueError(f"logit_bias token id {tid} is negative")
        if not -100.0 <= bias <= 100.0:
            raise ValueError(f"logit_bias value {bias} outside [-100, 100]")
        out[tid] = bias
    return out or None


def validate_structured_body(body: dict) -> None:
    """Tokenizer-free structural validation of an OpenAI request body; raises
    ValueError (-> 400) on malformed structured fields. Runs at the router
    (before flow control) and the engine frontend (before admission)."""
    rf = body.get("response_format")
    if rf is not None:
        if not isinstance(rf, dict):
            raise ValueError("response_format must be an object")
        typ = rf.get("type")
        if typ not in ("text", "json_object", "json_schema"):
            raise ValueError(f"unsupported response_format.type {typ!r}")
    parse_logit_bias(body.get("logit_bias"))
    sampling_like = _BodyView(body)
    spec = structured_spec(sampling_like)
    if spec is not None:
        # full lowering to the char-level automaton: catches unsupported
        # schema constructs AND unsatisfiable patterns, without the vocab lift
        compile_regex(spec_to_regex(*spec))


class _BodyView:
    """Duck-types a raw request body as SamplingParams for structured_spec."""

    def __init__(self, body: dict):
        self.guided_choice = body.get("guided_choice")
        self.guided_regex = body.get("guided_regex")
        self.response_format = body.get("response_format")


def grammar_key(kind: str, regex: str, tokenizer, vocab_size: int) -> tuple:
    fingerprint = (type(tokenizer).__name__, tokenizer.vocab_size,
                   tokenizer.eos_id)
    return (fingerprint, kind,
            hashlib.sha256(regex.encode()).hexdigest(), vocab_size)


def compile_grammar(kind: str, payload, tokenizer, vocab_size: int,
                    cache: Optional[GrammarCache] = None) -> tuple[TokenGrammar, bool]:
    """Compile (or fetch) the token grammar for a request. Returns
    (grammar, cache_hit); raises ValueError on malformed specs."""
    regex = spec_to_regex(kind, payload)
    cache = cache if cache is not None else global_cache()

    def build() -> TokenGrammar:
        return TokenGrammar(compile_regex(regex),
                            token_strings(tokenizer, vocab_size),
                            tokenizer.eos_id, vocab_size)

    return cache.get_or_compile(
        grammar_key(kind, regex, tokenizer, vocab_size), build)


def canonical_payload(kind: str, payload) -> str:
    """Stable textual form of a spec (flight-recorder provenance)."""
    if kind == "json_schema":
        return json.dumps(payload, sort_keys=True)
    if kind == "choice":
        return json.dumps(payload)
    return str(payload)
