"""JSON Schema -> regex translation (+ a matching subset validator).

The Outlines recipe (Willard & Louf 2023): lower a schema to a regular
expression describing its *serialized* form, then compile that through
``regex_dfa``. The emitted language is compact JSON — no inter-token
whitespace — which keeps the DFA small and, more importantly, keeps greedy
decoding from parking on a whitespace self-loop forever.

Supported subset (anything else raises ``ValueError`` -> HTTP 400):

* ``type``: string (minLength/maxLength/pattern), integer, number, boolean,
  null, object, array; a list of types becomes an alternation
* ``enum`` / ``const`` of scalars
* object: ``properties`` emitted in declaration order; when ``required`` is
  present, exactly the required properties are emitted (optional-property
  comma placement is the classic DFA blow-up — out of scope)
* array: ``items`` schema with ``minItems``/``maxItems``

``validate_instance`` checks a parsed value against the same subset, so
tests and ``tools/structured_check.py`` can assert corpus validity without a
jsonschema dependency (the image does not ship one).
"""

from __future__ import annotations

import json
import os

from llmd_tpu.structured.regex_dfa import MAX_REPEAT, escape_literal

# JSON string *content* chars: the universe minus `"`, `\`, and the raw
# control chars JSON forbids unescaped (we never emit escape sequences).
_STR_CHAR = '[^"\\\\\\t\\n\\r]'
_INTEGER = "-?(0|[1-9][0-9]*)"
_NUMBER = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"

# Generic `response_format: {"type": "json_object"}` has no schema to guide
# it; a DFA cannot count brackets, so nesting is bounded (XGrammar's pushdown
# avoids this; a depth-bounded FSM is the honest regex-only version).
DEFAULT_JSON_DEPTH = 3


def json_object_depth() -> int:
    try:
        return max(1, int(os.environ.get("LLMD_STRUCTURED_JSON_DEPTH",
                                         str(DEFAULT_JSON_DEPTH))))
    except ValueError:
        return DEFAULT_JSON_DEPTH


def _string_regex(schema: dict) -> str:
    if "pattern" in schema:
        pat = str(schema["pattern"])
        return '"' + pat.lstrip("^").rstrip("$") + '"'
    lo = int(schema.get("minLength", 0))
    hi = schema.get("maxLength")
    if hi is None:
        body = f"{_STR_CHAR}*" if lo == 0 else f"{_STR_CHAR}{{{lo},}}"
    else:
        if int(hi) > MAX_REPEAT:
            raise ValueError(f"maxLength {hi} exceeds supported {MAX_REPEAT}")
        body = f"{_STR_CHAR}{{{lo},{int(hi)}}}"
    return f'"{body}"'


def _literal_regex(value) -> str:
    if isinstance(value, (dict, list)):
        raise ValueError("enum/const members must be scalars")
    return escape_literal(json.dumps(value))


def _array_regex(schema: dict) -> str:
    item = regex_for_schema(schema.get("items", {"type": "string"}))
    lo = int(schema.get("minItems", 0))
    hi = schema.get("maxItems")
    if hi is not None and (int(hi) < lo or int(hi) > MAX_REPEAT):
        raise ValueError(f"bad minItems/maxItems ({lo}, {hi})")
    if hi is not None and int(hi) == 0:
        return r"\[\]"
    head = ",".join([item] * max(lo, 1))
    if hi is None:
        tail = f"(,{item})*"
    else:
        tail = f"(,{item}){{0,{int(hi) - max(lo, 1)}}}" if int(hi) > max(lo, 1) else ""
    body = head + tail
    if lo == 0:
        body = f"({body})?"
    return rf"\[{body}\]"


def _object_regex(schema: dict) -> str:
    props = schema.get("properties", {})
    if not isinstance(props, dict):
        raise ValueError("object properties must be a mapping")
    required = schema.get("required")
    if required is not None:
        missing = [k for k in required if k not in props]
        if missing:
            raise ValueError(f"required properties without a schema: {missing}")
        emit = [k for k in props if k in set(required)]
    else:
        emit = list(props)
    if not emit:
        return r"\{\}"
    fields = ",".join(
        f'"{escape_literal(k)}":{regex_for_schema(props[k])}' for k in emit)
    return rf"\{{{fields}\}}"


def regex_for_schema(schema: dict) -> str:
    """Regex for the compact serialization of values matching ``schema``."""
    if not isinstance(schema, dict):
        raise ValueError("schema must be an object")
    if "enum" in schema:
        return "(" + "|".join(_literal_regex(v) for v in schema["enum"]) + ")"
    if "const" in schema:
        return _literal_regex(schema["const"])
    typ = schema.get("type")
    if isinstance(typ, list):
        return ("(" + "|".join(regex_for_schema({**schema, "type": t})
                               for t in typ) + ")")
    if typ == "string":
        return _string_regex(schema)
    if typ == "integer":
        return _INTEGER
    if typ == "number":
        return _NUMBER
    if typ == "boolean":
        return "(true|false)"
    if typ == "null":
        return "null"
    if typ == "object":
        return _object_regex(schema)
    if typ == "array":
        return _array_regex(schema)
    if typ is None:
        raise ValueError("schema needs a type, enum, or const")
    raise ValueError(f"unsupported schema type {typ!r}")


def json_object_regex(depth: int | None = None) -> str:
    """Regex for generic JSON (``json_object`` mode), nesting bounded."""
    scalar = f'("{_STR_CHAR}*"|{_NUMBER}|true|false|null)'
    value = scalar
    obj = ""
    for _ in range(depth if depth is not None else json_object_depth()):
        member = f'"{_STR_CHAR}*":{value}'
        obj = rf"\{{({member}(,{member})*)?\}}"
        arr = rf"\[({value}(,{value})*)?\]"
        value = f"({scalar}|{obj}|{arr})"
    return obj  # OpenAI json_object mode: the top level must be an object


# ------------------------------------------------------------- validator


def validate_instance(value, schema: dict) -> bool:
    """Subset validator matching regex_for_schema's semantics."""
    if "enum" in schema:
        return value in schema["enum"]
    if "const" in schema:
        return value == schema["const"]
    typ = schema.get("type")
    if isinstance(typ, list):
        return any(validate_instance(value, {**schema, "type": t})
                   for t in typ)
    if typ == "string":
        if not isinstance(value, str):
            return False
        if len(value) < int(schema.get("minLength", 0)):
            return False
        hi = schema.get("maxLength")
        return hi is None or len(value) <= int(hi)
    if typ == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if typ == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if typ == "boolean":
        return isinstance(value, bool)
    if typ == "null":
        return value is None
    if typ == "object":
        if not isinstance(value, dict):
            return False
        props = schema.get("properties", {})
        for k in schema.get("required", list(props)):
            if k not in value:
                return False
        return all(k not in props or validate_instance(v, props[k])
                   for k, v in value.items())
    if typ == "array":
        if not isinstance(value, list):
            return False
        if len(value) < int(schema.get("minItems", 0)):
            return False
        hi = schema.get("maxItems")
        if hi is not None and len(value) > int(hi):
            return False
        item = schema.get("items")
        return item is None or all(validate_instance(v, item) for v in value)
    return True
