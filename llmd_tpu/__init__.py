"""llmd-tpu: a TPU-native distributed LLM inference framework.

Re-implements the capabilities of llm-d (reference: /root/reference) TPU-first:

- ``llmd_tpu.engine``    — JAX/Pallas serving engine (continuous batching, paged KV,
  chunked prefill, TP/DP/EP via pjit+shard_map over a ``jax.sharding.Mesh``).
- ``llmd_tpu.models``    — model families (dense Llama-style, MoE Qwen/DeepSeek-style).
- ``llmd_tpu.ops``       — Pallas TPU kernels (ragged paged attention, MoE grouped GEMM).
- ``llmd_tpu.parallel``  — mesh/sharding layer: TP, DP, EP all-to-all, sequence parallel.
- ``llmd_tpu.router``    — the EPP equivalent: parsers, data layer, Filter→Score→Pick
  scheduler, flow control, disaggregation profile handler.
- ``llmd_tpu.kv``        — KV-cache plane: event bus, prefix indexer, offload tiers.
- ``llmd_tpu.disagg``    — P/D disaggregation: routing sidecar + KV-transfer connector.

The reference is a Kubernetes-native orchestration stack over vLLM (llm-d
docs/architecture/README.md:5-64); here both the orchestration layer AND the engine are
provided, with the engine built TPU-native (XLA collectives over ICI/DCN instead of
NCCL/NVSHMEM/NIXL).
"""

__version__ = "0.1.0"
