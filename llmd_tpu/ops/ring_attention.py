"""Ring attention: context-parallel causal attention over the ``sp`` mesh axis.

The long-context design the task calls first-class: a sequence too long for one
chip's HBM shards across the ``sp`` axis; each device holds S/N query and KV
tokens, and attention runs in N ring steps — compute the partial attention of
local queries against the resident KV block, then ``ppermute`` the KV block to
the next device, overlapping the collective with the next block's compute (XLA
schedules the permute against the matmuls; ICI bandwidth hides behind MXU time
at serving block sizes).

Numerics: online softmax (flash-attention style running max/denominator), so
the result is exact attention — not an approximation — regardless of ring
order. Causality is resolved block-wise: a KV block strictly newer than every
local query contributes nothing (its lanes are masked), the diagonal block gets
the triangular mask, older blocks attend fully.

This is the context-parallel ATTENTION OP for the sharded long-prefill path —
self-contained and oracle-tested here; engine integration (routing sp-sharded
prefill chunks through it instead of the GSPMD-gathered path) is the follow-up.
The serving engine's paged decode keeps per-sequence KV local either way
(decode reads are tiny — sp parallelism pays off in prefill, where the S² term
lives). `sp_flash_prefill` below is the jittable entry: q/k/v arrive already
sharded on the sequence axis under `shard_map`.

Reference framing: the CUDA stacks reach for ring/context parallelism via NCCL
P2P; here the ring is `jax.lax.ppermute` over ICI — the collective the "How to
Scale Your Model" recipe prescribes for sequence parallelism.

Load balance: contiguous sharding leaves the causal ring imbalanced (the last
shard computes at every ring step while shard 0 computes once, and ppermute
synchronizes each step). ``sp_flash_prefill`` therefore defaults to ZIG-ZAG
partitioning — each device holds one chunk from each END of the sequence, so
causal work is ~equal per device per step — with the natural↔zig-zag
permutation handled inside the entry point (identical results either way,
oracle-tested).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, mask, m_prev, l_prev, acc_prev, scale):
    """One KV block's contribution under online softmax, GQA-native.

    q: [Sq, Hk, G, D] (query heads grouped under their KV head — head h of
    the flat [Sq, H] layout is (h // G, h % G) here); k/v: [Sk, Hk, D];
    mask: [Sq, Sk] (True = attend). Carries m (running max, [Sq, Hk, G]),
    l (running denom), acc ([Sq, Hk, G, D]). Keeping k/v at Hk heads is what
    the grouped layout buys: the ring's ppermute moves Hk-width KV blocks
    over ICI instead of H-width repeats (4x less wire traffic at llama
    shapes), while every query head still attends its group's KV.
    """
    s = jnp.einsum("qhgd,khd->qhgk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale  # [Sq, Hk, G, Sk]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    m_new = jnp.maximum(m_prev, s.max(axis=-1))  # [Sq, Hk, G]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    alive = m_new > NEG_INF / 2
    p = jnp.exp(jnp.where(alive[..., None], s - m_new[..., None], NEG_INF))
    correction = jnp.exp(jnp.where(alive, m_prev - m_new, 0.0))
    l_new = l_prev * correction + p.sum(axis=-1)
    acc_new = acc_prev * correction[..., None] + jnp.einsum(
        "qhgk,khd->qhgd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def _guarded_attn(pred, q, k, v, mask, m, l, acc, scale):
    """Run the block attend only when ``pred`` (traced bool) says it can
    contribute; identity carry otherwise — masked-out blocks never touch the
    MXU."""
    return lax.cond(
        pred,
        lambda args: _block_attn(*args, scale),
        lambda args: (args[4], args[5], args[6]),
        (q, k, v, mask, m, l, acc),
    )


def ring_attention_sharded(q, k, v, *, axis_name: str, scale: float,
                           shard_index: Optional[jax.Array] = None,
                           zigzag: bool = False):
    """Exact causal attention for sequence-sharded q/k/v inside ``shard_map``.

    q: [S_local, H, D]; k, v: [S_local, Hk, D] with H a multiple of Hk (GQA;
    Hk == H is plain MHA) — this device's slice of the sequence. Contiguous
    layout: shard s holds positions s*S_local... Zig-zag layout
    (``zigzag=True``): shard s holds chunk s then chunk 2n-1-s (each C =
    S_local/2 rows) — the balanced schedule where every device runs exactly
    two C×C sub-attends per ring step (lo-key→hi-query always; plus lo→lo when
    src≤my or hi→hi when src≥my), instead of the contiguous ring's worst shard
    paying the full block at every step. Returns [S_local, H, D].
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name) if shard_index is None else shard_index
    S, H, D = q.shape
    Hk = k.shape[1]
    G = H // Hk  # flat head h lives at (h // G, h % G) in the grouped layout
    q = q.reshape(S, Hk, G, D)

    def step_contiguous(carry, i):
        kv, m, l, acc = carry
        kb, vb = kv
        src = (my - i) % n  # whose block we hold at ring step i
        # causality by GLOBAL position: queries attend keys at k_pos <= q_pos
        q_pos = my * S + jnp.arange(S)
        k_pos = src * S + jnp.arange(S)
        mask = k_pos[None, :] <= q_pos[:, None]
        # strictly-future blocks skip the einsums entirely: causal ring does
        # ~n²/2 useful block-attends and the rest must stay off the MXU
        m, l, acc = _guarded_attn(src <= my, q, kb, vb, mask, m, l, acc, scale)
        return _rotate(kv, kb, vb, m, l, acc, i)

    def step_zigzag(carry, i):
        kv, m, l, acc = carry
        kb, vb = kv
        src = (my - i) % n
        C = S // 2
        ar = jnp.arange(C)
        q_lo_pos, q_hi_pos = my * C + ar, (2 * n - 1 - my) * C + ar
        k_lo_pos, k_hi_pos = src * C + ar, (2 * n - 1 - src) * C + ar
        (q_lo, q_hi), (k_lo, k_hi), (v_lo, v_hi) = (
            (t[:C], t[C:]) for t in (q, kb, vb))
        m_lo, m_hi = m[:C], m[C:]
        l_lo, l_hi = l[:C], l[C:]
        a_lo, a_hi = acc[:C], acc[C:]
        # (k_lo → q_lo): same-or-older low chunk; triangular iff src == my
        m_lo, l_lo, a_lo = _guarded_attn(
            src <= my, q_lo, k_lo, v_lo,
            k_lo_pos[None, :] <= q_lo_pos[:, None], m_lo, l_lo, a_lo, scale)
        # (k_lo → q_hi): every low chunk precedes every high chunk — always on
        m_hi, l_hi, a_hi = _block_attn(
            q_hi, k_lo, v_lo, k_lo_pos[None, :] <= q_hi_pos[:, None],
            m_hi, l_hi, a_hi, scale)
        # (k_hi → q_hi): high chunks order REVERSES with shard id
        m_hi, l_hi, a_hi = _guarded_attn(
            src >= my, q_hi, k_hi, v_hi,
            k_hi_pos[None, :] <= q_hi_pos[:, None], m_hi, l_hi, a_hi, scale)
        # (k_hi → q_lo): strictly future for every pair — never computed
        m = jnp.concatenate([m_lo, m_hi])
        l = jnp.concatenate([l_lo, l_hi])
        acc = jnp.concatenate([a_lo, a_hi])
        return _rotate(kv, kb, vb, m, l, acc, i)
    def _rotate(kv, kb, vb, m, l, acc, i):
        # rotate KV around the ring: device d hands its block to d+1. The final
        # iteration's rotation would feed nothing — skip the collective (i is
        # uniform across devices, so every device takes the same branch).
        kv = lax.cond(
            i < n - 1,
            lambda t: jax.tree.map(
                lambda x: lax.ppermute(
                    x, axis_name, [(j, (j + 1) % n) for j in range(n)]), t),
            lambda t: t,
            (kb, vb),
        )
        return (kv, m, l, acc), None

    step = step_zigzag if zigzag else step_contiguous

    # the zero-init carries are device-invariant but the loop outputs vary
    # over the ring axis — shard_map's varying-axes check requires the carry
    # types to agree up front (pcast on current jax; pvary on older)
    def _mark_varying(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, axis_name, to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(x, axis_name)
        return x  # pre-0.5 jax has no varying-axes check — nothing to satisfy

    m0 = _mark_varying(jnp.full((S, Hk, G), NEG_INF, jnp.float32))
    l0 = _mark_varying(jnp.zeros((S, Hk, G), jnp.float32))
    acc0 = _mark_varying(jnp.zeros((S, Hk, G, D), jnp.float32))
    (kv, m, l, acc), _ = lax.scan(
        step, ((k, v), m0, l0, acc0), jnp.arange(n, dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(S, H, D).astype(q.dtype)


def sp_flash_prefill(q, k, v, mesh, *, scale: Optional[float] = None,
                     axis_name: str = "sp", zigzag: bool = True):
    """Jittable entry: full-sequence q [S, H, D], k/v [S, Hk, D] (GQA when
    Hk < H) → causal attention [S, H, D], computed ring-parallel over
    ``mesh``'s ``axis_name`` axis. S must divide evenly by 2× the axis size
    (pad upstream — the engine's chunking already works in page multiples).

    ``zigzag=True`` (default) assigns each device one chunk from EACH END of
    the sequence (device d holds chunks d and 2n-1-d), so causal work is
    ~equal per device per ring step — the contiguous layout leaves the last
    shard computing at every step while shard 0 idles behind the ppermute
    barrier, ~2× the wall clock for identical results."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(axis_name, None, None)
    n = mesh.shape[axis_name]
    S = q.shape[0]

    use_zigzag = zigzag and n > 1 and S % (2 * n) == 0
    if zigzag and n > 1 and not use_zigzag:
        # zig-zag needs S divisible by 2n; contiguous only needs n. Degrade
        # loudly-enough (perf property, not correctness) rather than truncate.
        import warnings

        warnings.warn(f"ring attention: S={S} not divisible by 2*{n}; "
                      "using the contiguous (imbalanced) layout")
    if S % n != 0:
        raise ValueError(f"sequence length {S} must divide by the {axis_name} "
                         f"axis size {n} (pad upstream)")
    if use_zigzag:
        C = S // (2 * n)
        # device d's rows: chunk d then chunk 2n-1-d (natural→zigzag gather is
        # a GSPMD permute at prefill scale — negligible next to the S² attends)
        chunk_ids = jnp.stack(
            [jnp.arange(n), 2 * n - 1 - jnp.arange(n)], axis=1).reshape(-1)
        perm = (chunk_ids[:, None] * C + jnp.arange(C)[None, :]).reshape(-1)
        inv = jnp.argsort(perm)
    else:
        perm = inv = None

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    def run(qs, ks, vs):
        return ring_attention_sharded(qs, ks, vs, axis_name=axis_name,
                                      scale=scale, zigzag=use_zigzag)

    if perm is None:
        return run(q, k, v)
    out = run(q[perm], k[perm], v[perm])
    return out[inv]


def make_ring_attn_impl(mesh, axis_name: str = "sp", zigzag: bool = True):
    """Uniform-signature attention impl (drop-in for the engine's
    ``attn_impl`` seam) that computes the step's attention ring-parallel over
    ``mesh``'s sp axis, from the chunk's own q/k/v instead of the paged cache.

    Valid ONLY for the self-contained prefill regime the engine gates host-side
    (`LLMEngine._step_unified`): a single fresh sequence packed at offset 0,
    positions 0..n-1, no prior KV — there, causality by row index equals
    causality by position, trailing pad rows attend nothing real (their keys
    sit strictly in every real query's future), and in-chunk q/k/v ARE the
    whole attention problem. KV still lands in the paged cache (write_kv runs
    before the attn call), so decode continues from the cache as usual.

    GQA-native: k/v ride the ring at their Hk head count (the grouped-head
    schedule in ``_block_attn``) — ppermute moves Hk-width KV blocks over
    ICI, not H-width repeats (4x less ring traffic at llama shapes).
    """

    def impl(q, layer_cache, page_tables, positions, seq_slots, kv_lens, *,
             scale, cu_q_lens=None, num_seqs=None, chunk_k=None, chunk_v=None):
        del layer_cache, page_tables, positions, seq_slots, kv_lens
        del cu_q_lens, num_seqs
        if chunk_k is None or chunk_v is None:
            raise ValueError("ring attn impl needs the chunk's raw k/v "
                             "(forward_core passes chunk_k/chunk_v)")
        return sp_flash_prefill(q, chunk_k, chunk_v, mesh, scale=scale,
                                axis_name=axis_name, zigzag=zigzag)

    return impl


def reference_causal_attention(q, k, v, scale: Optional[float] = None):
    """Dense causal attention (the correctness oracle for the ring path);
    GQA k/v are repeated up to the query head count here — the oracle pays
    the bandwidth the ring exists to avoid."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if k.shape[1] != q.shape[1]:
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    S = q.shape[0]
    s = jnp.einsum("qhd,khd->qhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("qhk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)
