"""Ring attention: context-parallel causal attention over the ``sp`` mesh axis.

The long-context design the task calls first-class: a sequence too long for one
chip's HBM shards across the ``sp`` axis; each device holds S/N query and KV
tokens, and attention runs in N ring steps — compute the partial attention of
local queries against the resident KV block, then ``ppermute`` the KV block to
the next device, overlapping the collective with the next block's compute (XLA
schedules the permute against the matmuls; ICI bandwidth hides behind MXU time
at serving block sizes).

Numerics: online softmax (flash-attention style running max/denominator), so
the result is exact attention — not an approximation — regardless of ring
order. Causality is resolved block-wise: a KV block strictly newer than every
local query contributes nothing (its lanes are masked), the diagonal block gets
the triangular mask, older blocks attend fully.

This is the context-parallel ATTENTION OP for the sharded long-prefill path —
self-contained and oracle-tested here; engine integration (routing sp-sharded
prefill chunks through it instead of the GSPMD-gathered path) is the follow-up.
The serving engine's paged decode keeps per-sequence KV local either way
(decode reads are tiny — sp parallelism pays off in prefill, where the S² term
lives). `sp_flash_prefill` below is the jittable entry: q/k/v arrive already
sharded on the sequence axis under `shard_map`.

Reference framing: the CUDA stacks reach for ring/context parallelism via NCCL
P2P; here the ring is `jax.lax.ppermute` over ICI — the collective the "How to
Scale Your Model" recipe prescribes for sequence parallelism.

Known follow-up: contiguous sharding leaves the causal ring load-imbalanced
(the last shard computes at every ring step while shard 0 computes once — the
skip only saves energy, not wall-clock, since ppermute synchronizes each
step). The standard fix is zig-zag partitioning: each device holds one chunk
from each END of the sequence, so every device does ~equal causal work per
step. That changes the slice-order contract with the caller; land it together
with the engine integration.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, mask, m_prev, l_prev, acc_prev, scale):
    """One KV block's contribution under online softmax.

    q: [Sq, H, D]; k/v: [Sk, H, D]; mask: [Sq, Sk] (True = attend).
    Carries m (running max, [Sq, H]), l (running denom), acc ([Sq, H, D]).
    """
    s = jnp.einsum("qhd,khd->qhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale  # [Sq, H, Sk]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    m_new = jnp.maximum(m_prev, s.max(axis=-1))  # [Sq, H]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    alive = m_new > NEG_INF / 2
    p = jnp.exp(jnp.where(alive[:, :, None], s - m_new[:, :, None], NEG_INF))
    correction = jnp.exp(jnp.where(alive, m_prev - m_new, 0.0))
    l_new = l_prev * correction + p.sum(axis=-1)
    acc_new = acc_prev * correction[:, :, None] + jnp.einsum(
        "qhk,khd->qhd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention_sharded(q, k, v, *, axis_name: str, scale: float,
                           shard_index: Optional[jax.Array] = None):
    """Exact causal attention for sequence-sharded q/k/v inside ``shard_map``.

    q, k, v: [S_local, H, D] — this device's contiguous slice of the sequence
    (slice order = position order along the axis). Returns [S_local, H, D].
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name) if shard_index is None else shard_index
    S, H, D = q.shape
    pos_local = jnp.arange(S)

    def step(carry, i):
        kv, m, l, acc = carry
        kb, vb = kv
        src_shard = (my - i) % n  # whose block we hold at ring step i
        # block-wise causality: queries at global q_pos attend keys at k_pos <= q_pos
        q_pos = my * S + pos_local  # [S]
        k_pos = src_shard * S + pos_local  # [S] (uniform shard size)
        mask = k_pos[None, :] <= q_pos[:, None]
        # strictly-future blocks (src_shard > my) are fully masked — skip their
        # einsums entirely: causal ring does ~n²/2 useful block-attends, and
        # paying all n² doubles the S² FLOPs this op exists to scale
        m, l, acc = lax.cond(
            src_shard <= my,
            lambda args: _block_attn(*args, scale),
            lambda args: (args[4], args[5], args[6]),
            (q, kb, vb, mask, m, l, acc),
        )
        # rotate KV around the ring: device d hands its block to d+1. The final
        # iteration's rotation would feed nothing — skip the collective (i is
        # uniform across devices, so every device takes the same branch).
        kv = lax.cond(
            i < n - 1,
            lambda t: jax.tree.map(
                lambda x: lax.ppermute(
                    x, axis_name, [(j, (j + 1) % n) for j in range(n)]), t),
            lambda t: t,
            (kb, vb),
        )
        return (kv, m, l, acc), None

    # the zero-init carries are device-invariant but the loop outputs vary
    # over the ring axis — shard_map's varying-axes check requires the carry
    # types to agree up front (pcast on current jax; pvary on older)
    def _mark_varying(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, axis_name, to="varying")
        return lax.pvary(x, axis_name)

    m0 = _mark_varying(jnp.full((S, H), NEG_INF, jnp.float32))
    l0 = _mark_varying(jnp.zeros((S, H), jnp.float32))
    acc0 = _mark_varying(jnp.zeros((S, H, D), jnp.float32))
    (kv, m, l, acc), _ = lax.scan(
        step, ((k, v), m0, l0, acc0), jnp.arange(n, dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-30)[:, :, None]
    return out.astype(q.dtype)


def sp_flash_prefill(q, k, v, mesh, *, scale: Optional[float] = None,
                     axis_name: str = "sp"):
    """Jittable entry: full-sequence q/k/v [S, H, D] → causal attention [S, H, D],
    computed ring-parallel over ``mesh``'s ``axis_name`` axis. S must divide
    evenly by the axis size (pad upstream — the engine's chunking already works
    in page multiples)."""
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(axis_name, None, None)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    def run(qs, ks, vs):
        return ring_attention_sharded(qs, ks, vs, axis_name=axis_name,
                                      scale=scale)

    return run(q, k, v)


def reference_causal_attention(q, k, v, scale: Optional[float] = None):
    """Dense causal attention (the correctness oracle for the ring path)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    S = q.shape[0]
    s = jnp.einsum("qhd,khd->qhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("qhk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)
