"""Pallas TPU kernels for the hot ops (the reference's FlashInfer/DeepGEMM slot,
SURVEY.md §2.5 N7-N8)."""

from llmd_tpu.ops.paged_attention import paged_attention_tpu

__all__ = ["paged_attention_tpu"]
