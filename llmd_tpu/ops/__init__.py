"""Pallas TPU kernels + collective attention for the hot ops (the reference's
FlashInfer/DeepGEMM slot, SURVEY.md §2.5 N7-N8; ring attention for sp)."""

from llmd_tpu.ops.paged_attention import paged_attention_tpu
from llmd_tpu.ops.ring_attention import ring_attention_sharded, sp_flash_prefill

__all__ = ["paged_attention_tpu", "ring_attention_sharded", "sp_flash_prefill"]
