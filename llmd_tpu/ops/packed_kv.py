"""Packed KV layout: reclaim the lane-padding share of the KV stream.

The ragged-paged-attention kernel requires head_dim padded to the 128-lane
tile ([P, ps, 2*Hk, Dhp] — models.transformer.init_cache). For head_dim-64
models (llama-1b, every Llama-3.2-class shape) that means HALF of every KV
byte DMA'd from HBM is zero padding: at serving batch 64 / ctx 320 the
padded bf16 KV read is ~1.3 GB per decode step, of which ~0.65 GB is zeros.

The fix is a layout, not a kernel: pack ``f = Dhp // Dh`` real KV heads into
ONE 128-lane row —

    packed cache [P, ps, 2*(Hk/f), f*Dh]    K of pack p = [k_{pf} | … | k_{pf+f-1}]

and give the stock kernel queries zero-padded into their head's lane slot,
so the per-head dot products are EXACT through the padding algebra:

    [0 … q … 0] . [k_{pf} | … | k_{pf+f-1}] = q . k_{pf+j}   (slot j)

Scores equal the per-head scores bitwise (the cross terms multiply exact
zeros), so softmax and the p@V product match the padded layout; each query
row's correct output slot is selected after the kernel. The kernel sees an
ordinary GQA problem with Hk/f KV heads of dim f*Dh and f*G queries per KV
head — no fork, no custom Mosaic. Grouping stays contiguous: q heads
[pfG, (p+1)fG) already map to real KV heads pf..pf+f-1 in slot order.

Eligible when padded_head_dim(Dh) == f*Dh exactly and Hk % f == 0; composes
with the fp8 pool (llama-1b: packed combined heads 8, fp8 strided-load
packing 4 divides it) for a combined 4x KV-stream cut vs padded bf16.
The zig-zag ring path is orthogonal — it attends over pre-cache chunk
activations, never the pool layout.

Reference baselines serve unpadded head_dim-64 KV natively on GPU
(FlashInfer has no lane-tile constraint); this restores that parity on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp


def pack_factor(cfg) -> int:
    """How many real KV heads share one lane row (1 = padded layout)."""
    from llmd_tpu.models.transformer import padded_head_dim

    if getattr(cfg, "is_mla", False):
        return 1  # one shared latent "head" per token; nothing to pack
    dhp = padded_head_dim(cfg.kv_cache_head_dim)
    f = dhp // cfg.kv_cache_head_dim
    if f > 1 and dhp == f * cfg.kv_cache_head_dim and cfg.kv_cache_heads % f == 0:
        return f
    return 1


def make_packed_attn(inner, cfg, f: int):
    """Wrap a uniform-signature paged-attention impl (Pallas or XLA reference)
    so it runs against the packed pool. ``inner`` sees q rows placed in their
    lane slot and the packed cache; callers keep the standard [N, H, Dhp]
    contract (forward_core slices [..., :Dh] after)."""
    Dh, H, Hk = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
    G = H // Hk  # q heads per real kv head
    eye = jnp.eye(f)

    def impl(q, layer_cache, page_tables, positions, seq_slots, kv_lens, *,
             scale, cu_q_lens=None, num_seqs=None, chunk_k=None, chunk_v=None):
        del chunk_k, chunk_v  # paged impls ignore them (ring never wraps)
        N = q.shape[0]
        qc = q[:, :, :Dh].reshape(N, Hk // f, f, G, Dh)
        # slot placement: head j of pack p → lanes [j*Dh, (j+1)*Dh)
        qp = jnp.einsum("npjgd,jk->npjgkd", qc, eye.astype(qc.dtype))
        qp = qp.reshape(N, H, f * Dh)
        out = inner(qp, layer_cache, page_tables, positions, seq_slots,
                    kv_lens, scale=scale, cu_q_lens=cu_q_lens,
                    num_seqs=num_seqs)
        o = out.reshape(N, Hk // f, f, G, f, Dh)
        merged = jnp.einsum("npjgkd,jk->npjgd", o, eye.astype(o.dtype))
        merged = merged.reshape(N, H, Dh)
        # back to the padded contract
        return jnp.pad(merged, ((0, 0), (0, 0), (0, (f - 1) * Dh)))

    return impl
