"""Pallas grouped (per-expert) GEMM — the TPU-native answer to DeepGEMM's masked
grouped FP8 GEMM (SURVEY.md §2.5 N7, docker/Dockerfile.cuda:68-69, wide-ep
decode.yaml `--moe-backend deep_gemm`).

``out[g] = x[g] @ w[g]`` for every expert group g, with a per-group valid count:
groups that received zero tokens this step skip their MXU work entirely
(``@pl.when`` on a scalar-prefetched count — the Pallas equivalent of DeepGEMM's
masked launch). Dense einsum can't do that: it always pays for all E experts even
when top-k routing touched a handful.

Layout: grid ``(G, C/bc, F/bf)``; each program computes one [bc, bf] output tile
with a single [bc, D] x [D, bf] MXU dot (fp32 accumulation, bf16 in). D is kept
whole — MoE expert widths (D <= 8k) fit VMEM at these tile sizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gg_kernel(counts_ref, x_ref, w_ref, o_ref):
    g = pl.program_id(0)

    @pl.when(counts_ref[g] > 0)
    def _compute():
        acc = jax.lax.dot_general(
            x_ref[0], w_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0] = acc.astype(o_ref.dtype)

    @pl.when(counts_ref[g] == 0)
    def _skip():
        o_ref[0] = jnp.zeros_like(o_ref[0])


@functools.partial(jax.jit, static_argnames=("interpret",))
def grouped_gemm(
    x: jax.Array,  # [G, C, D]
    w: jax.Array,  # [G, D, F]
    counts: jax.Array,  # [G] int32 — tokens routed to each group this step
    interpret: bool | None = None,
) -> jax.Array:  # [G, C, F]
    """Per-group matmul with zero-token groups skipped on the MXU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    G, C, D = x.shape
    _, _, F = w.shape

    bc = min(128, 8 * ((C + 7) // 8))   # capped: a [bc, D] block must fit VMEM
    bf = min(256, 128 * ((F + 127) // 128))
    # pad C and F up to tile multiples (token capacity C is often small/ragged)
    Cp, Fp = -(-C // bc) * bc, -(-F // bf) * bf
    if Cp != C:
        x = jnp.pad(x, ((0, 0), (0, Cp - C), (0, 0)))
    if Fp != F:
        w = jnp.pad(w, ((0, 0), (0, 0), (0, Fp - F)))

    out = pl.pallas_call(
        _gg_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(G, Cp // bc, Fp // bf),
            in_specs=[
                pl.BlockSpec((1, bc, D), lambda g, i, j, counts: (g, i, 0)),
                pl.BlockSpec((1, D, bf), lambda g, i, j, counts: (g, 0, j)),
            ],
            out_specs=pl.BlockSpec((1, bc, bf), lambda g, i, j, counts: (g, i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((G, Cp, Fp), x.dtype),
        interpret=interpret,
    )(counts.astype(jnp.int32), x, w)
    return out[:, :C, :F]


def _rgg_kernel(slots_ref, rows_ref, x_ref, w_ref, o_ref):
    b = pl.program_id(0)

    @pl.when(rows_ref[b] > 0)
    def _compute():
        acc = jax.lax.dot_general(
            x_ref[0], w_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0] = acc.astype(o_ref.dtype)

    @pl.when(rows_ref[b] == 0)
    def _skip():
        o_ref[0] = jnp.zeros_like(o_ref[0])


@functools.partial(jax.jit, static_argnames=("interpret",))
def ragged_grouped_gemm(
    x: jax.Array,  # [nb, bc, D] — token-sorted block-aligned activations
    w: jax.Array,  # [S, D, F] — expert slot bank
    block_slot: jax.Array,  # [nb] int32 — expert slot owning each block
    block_rows: jax.Array,  # [nb] int32 — real rows in each block
    interpret: bool | None = None,
) -> jax.Array:  # [nb, bc, F]
    """Block-ragged grouped GEMM for the token-sorted dispatch path
    (ops/moe_dispatch): each [bc, D] block multiplies the weight of the
    slot it belongs to — the slot id rides in scalar prefetch so the
    weight DMA is indexed per block, and fully-padded blocks skip their
    MXU work just like zero-count groups in ``grouped_gemm``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb, bc, D = x.shape
    _, _, F = w.shape

    bf = min(256, 128 * ((F + 127) // 128))
    Fp = -(-F // bf) * bf
    if Fp != F:
        w = jnp.pad(w, ((0, 0), (0, 0), (0, Fp - F)))

    out = pl.pallas_call(
        _rgg_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(nb, Fp // bf),
            in_specs=[
                pl.BlockSpec((1, bc, D), lambda b, j, slots, rows: (b, 0, 0)),
                pl.BlockSpec((1, D, bf),
                             lambda b, j, slots, rows: (slots[b], 0, j)),
            ],
            out_specs=pl.BlockSpec((1, bc, bf),
                                   lambda b, j, slots, rows: (b, 0, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((nb, bc, Fp), x.dtype),
        interpret=interpret,
    )(block_slot.astype(jnp.int32), block_rows.astype(jnp.int32), x, w)
    return out[:, :, :F]


def make_moe_matmul(interpret: bool | None = None):
    """Adapter with the ``moe_block`` matmul_impl signature."""
    def impl(xe, we, slot_counts):
        return grouped_gemm(xe, we, slot_counts, interpret=interpret)
    return impl
