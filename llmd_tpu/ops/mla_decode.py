"""Latent-width Pallas decode attention for the single-plane MLA pool.

Absorbed MLA decode is MQA whose "head dim" is the latent width rank+rope
(288–640 on DeepSeek-class shapes) — past the upstream ragged-paged-attention
kernel's supported head sizes, which is why MLA decode historically fell back
to the XLA gather+mask reference (`models.transformer.ragged_paged_attention_xla`).
This module is the Pallas path that closes that gap.

Why a bespoke kernel is *easier* here than for GQA:

- the pool is a SINGLE plane per token (`init_cache` HkC == 1): keys and
  values are the same [c_kv ; k_rope] latent row, so one page DMA feeds both
  the score dot and the p@V product — the kernel streams each page once,
- decode is one query row per sequence (N == B), so the grid is simply
  (sequences, pages) with the page table scalar-prefetched to drive the KV
  block index_map — Pallas double-buffers consecutive page fetches,
- **latent width needs no lane alignment games**: the pool pads the latent to
  ``padded_head_dim(rank+rope)`` with zeros and `forward_core` zero-pads the
  query the same way, so full padded-width dot products equal the real-width
  dots exactly — the same slot-placement algebra `ops/packed_kv.py` uses
  ([0…q…0]·[kv|0…0] = q·kv; the cross terms multiply exact zeros). The kernel
  just runs at Dhp and parity with the reference is bitwise in fp32.

Softmax is the standard online (flash) recurrence over pages with VMEM
scratch carrying (m, l, acc) per sequence; rows whose kv_len is 0 (idle
decode slots) produce exact zeros. Off-TPU the kernel runs in interpreter
mode so CPU-mesh tests, parity pins, and the `bench-tiny-attn` CI stage
execute the same code path the TPU compiles.

Scope: DECODE shapes only (one query per sequence, causality == attend to
the whole resident prefix). Mixed prefill/chunk batches keep the XLA
reference path — the engine installs this impl on the fused-decode program
alone (`engine._select_attn_impl`).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llmd_tpu.ops.paged_attention import VMEM_LIMIT

# Large-negative finite mask value: -inf would make the m/alpha recurrence
# produce nan on fully masked pages (exp(-inf - -inf)); masked probabilities
# are zeroed explicitly as well.
NEG_INF = -0.7 * float(np.finfo(np.float32).max)

# Minor (lane) width of the m/l scratch rows. TPU vector ops want a 128-lane
# minor dim; only column 0 is meaningful.
_MINOR = 128


def _decode_kernel(page_tables_ref, kv_lens_ref,  # scalar prefetch
                   q_ref, kv_ref, o_ref,          # blocks
                   m_ref, l_ref, acc_ref):        # VMEM scratch
    """Grid (b, p): sequence b consumes its p-th page. Scratch carries the
    online-softmax state across the page axis; p == 0 resets it, the last
    page normalizes and writes the output row."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    num_page_steps = pl.num_programs(1)
    ps = kv_ref.shape[0]

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # tokens on this page that exist: [p*ps, min((p+1)*ps, kv_len))
    n_valid = jnp.clip(kv_lens_ref[b] - p * ps, 0, ps)

    @pl.when(n_valid > 0)
    def _page():
        q = q_ref[0].astype(jnp.float32)        # [H, Dhp] (pre-scaled)
        kv = kv_ref[...].astype(jnp.float32)    # [ps, Dhp] shared latent: k == v
        s = jax.lax.dot_general(                # [H, ps]
            q, kv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        tok = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = tok < n_valid
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[...]                     # [H, _MINOR]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)
        pij = jnp.exp(s - m_new[:, :1])
        pij = jnp.where(mask, pij, 0.0)         # fully masked rows stay 0
        l_ref[...] = l_ref[...] * alpha + jnp.broadcast_to(
            jnp.sum(pij, axis=1, keepdims=True), alpha.shape)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jax.lax.dot_general(
            pij, kv, (((1,), (0,)), ((), ())),  # p @ V, V == the same latents
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(p == num_page_steps - 1)
    def _finish():
        l = l_ref[:, :1]
        # kv_len == 0 (idle slot): l stays 0 → exact-zero output row, the
        # same contract as the XLA reference (callers ignore idle rows)
        o_ref[0] = jnp.where(
            l > 0.0, acc_ref[...] / jnp.where(l > 0.0, l, 1.0), 0.0
        ).astype(o_ref.dtype)


def mla_decode_pallas(
    q: jax.Array,            # [B, H, Dhp] one query row per sequence
    layer_cache: jax.Array,  # [P, ps, 1, Dhp] single-plane latent pool
    page_tables: jax.Array,  # [B, maxp] (already clamped >= 0)
    kv_lens: jax.Array,      # [B] tokens resident incl. this step's
    *,
    scale: float,
    interpret: "bool | None" = None,
) -> jax.Array:
    """Raw kernel invocation (decode shapes). Returns [B, H, Dhp]; lanes past
    the real latent width come back zero (acc only mixes stored rows, whose
    pad lanes are zero)."""
    B, H, Dhp = q.shape
    _, ps, planes, _ = layer_cache.shape
    assert planes == 1, "mla_decode_pallas serves the single-plane latent pool"
    maxp = page_tables.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # fold sm_scale into q once (f32 exact: scale is a power-free float but
    # the same value the reference multiplies into the scores)
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, maxp),
        in_specs=[
            pl.BlockSpec((1, H, Dhp), lambda b, p, pt, kl: (b, 0, 0)),
            # one KV page per grid step, gathered through the prefetched page
            # table (Pallas pipelines the next page's DMA behind this page's
            # compute); the plane axis is squeezed away
            pl.BlockSpec((None, ps, None, Dhp),
                         lambda b, p, pt, kl: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, Dhp), lambda b, p, pt, kl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, _MINOR), jnp.float32),  # m
            pltpu.VMEM((H, _MINOR), jnp.float32),  # l
            pltpu.VMEM((H, Dhp), jnp.float32),     # acc
        ],
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            # revisit-heavy grid: neither axis is parallelizable (scratch
            # carries state across pages; output blocks revisit across b)
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=VMEM_LIMIT,
        )
    kern = pl.pallas_call(
        _decode_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dhp), q.dtype),
        interpret=interpret,
        **kwargs,
    )
    return kern(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
                q, layer_cache)


def mla_paged_attention_latent(
    q: jax.Array,            # [N, H, Dhp] flat query tokens (lane-padded)
    layer_cache: jax.Array,  # [P, ps, 1, Dhp]
    page_tables: jax.Array,  # [B, maxp] (-1 = unmapped)
    positions: jax.Array,    # [N] (unused: decode attends to the full prefix)
    seq_slots: jax.Array,    # [N] (unused: row i IS sequence i at decode)
    kv_lens: jax.Array,      # [B]
    *,
    scale: float,
    cu_q_lens: "jax.Array | None" = None,   # unused (uniform impl signature)
    num_seqs: "jax.Array | None" = None,    # unused (uniform impl signature)
    chunk_k: "jax.Array | None" = None,     # unused (ring-attn impls only)
    chunk_v: "jax.Array | None" = None,     # unused (ring-attn impls only)
) -> jax.Array:
    """Uniform-signature adapter (drop-in for ragged_paged_attention_xla) for
    DECODE calls on MLA engines: one query row per batch slot. The engine
    installs this on the fused-decode program only; unified/verify/embed
    programs (mixed chunk shapes) keep the reference impl.
    """
    del positions, seq_slots, cu_q_lens, num_seqs, chunk_k, chunk_v
    assert q.shape[0] == page_tables.shape[0], (
        "latent decode kernel requires one query row per sequence "
        f"(got N={q.shape[0]}, B={page_tables.shape[0]}); route mixed "
        "batches through the XLA reference impl")
    # -1 marks unmapped table entries; those pages lie at/past kv_len so the
    # kernel never weighs them — clamp for the prefetched DMA's sake only
    page_tables = jnp.maximum(page_tables, 0)
    if layer_cache.dtype == jnp.float8_e4m3fn:
        # fp8 latent pages: mirror the GQA kernel's in-VMEM dequant semantics.
        # write_kv stores the latent at scale 1.0, so upcasting at use is the
        # whole dequant; the kernel's f32 compute path does it for free.
        layer_cache = layer_cache.astype(q.dtype)
    return mla_decode_pallas(q, layer_cache, page_tables, kv_lens, scale=scale)
