"""Shape-keyed attention block-size auto-tune table.

`pick_block_sizes` started life as a static heuristic swept once on v5e at
llama-1b/B=32 shapes, later patched with `LLMD_ATTN_BKV`/`LLMD_ATTN_BQ` env
overrides written by bench.py's on-chip tuner. Both share a flaw: one global
answer. The optimum moves with (batch, pages_per_seq, head layout) — the env
override tuned at b32 is exactly the "block sizes chosen for batch-32" running
at batch-128 that the r05 campaign exposed (PERF.md Round 6).

This module replaces the single-winner scheme with a persistent, shape-keyed
table:

- bench.py's auto-tuner times candidates at each serving shape it visits and
  **merges** winners into a JSON cache file (one entry per shape key, newest
  wins), so a campaign accumulates a per-chip table across points,
- the engine loads the file at startup (`EngineConfig.attn_tune_file` or
  ``LLMD_ATTN_TUNE_FILE``) and `pick_block_sizes` consults it before the
  heuristic; the env overrides still win over both (operator escape hatch),
- provenance: `table_hash()` is reported by engine stats and bench JSON so a
  measured number can be traced to the exact table that shaped its kernels.

File format (version 1)::

    {"version": 1,
     "entries": [{"batch": 64, "page_size": 64, "pages_per_seq": 8,
                  "head_layout": "h16x128kv8", "bkv": 2, "bq": 32,
                  "us_per_call": 123.4, "tuned_on": "TPU v5e"}, ...]}

Lookup requires an exact (batch, page_size, head_layout) match — block sizes
tuned for one head geometry or page size say nothing about another — and takes
the entry with the **nearest pages_per_seq** (tables grow with max_model_len;
a b128 entry tuned at 8 pages/seq is still the best available answer at 10).

A missing, unreadable, or corrupt file degrades to the heuristic with a
warning — never an engine-startup failure. Malformed entries are dropped
individually so one bad merge doesn't void a whole campaign's table.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from dataclasses import dataclass, field

log = logging.getLogger("llmd_tpu.attn_tune")

ENV_TUNE_FILE = "LLMD_ATTN_TUNE_FILE"

_REQUIRED_INT_FIELDS = ("batch", "page_size", "pages_per_seq", "bkv", "bq")


def head_layout_key(num_q_heads: int, head_dim_padded: int, kv_planes: int) -> str:
    """Canonical head-layout key: query heads x padded head width, KV planes
    per token (2*Hk for the combined GQA layout, 1 for the MLA latent plane,
    2*Hk/kv_pack when slot-packed)."""
    return f"h{num_q_heads}x{head_dim_padded}kv{kv_planes}"


@dataclass(frozen=True)
class AttnTuneTable:
    """Validated, immutable view of a tune file."""

    entries: tuple = ()
    source: str = ""
    sha: str = ""  # short content hash of the *valid* entries, for provenance
    dropped: int = 0  # malformed entries discarded at load

    def lookup(self, batch: int, page_size: int, pages_per_seq: int,
               head_layout: "str | None") -> "tuple[int, int] | None":
        best = None
        for e in self.entries:
            if e["batch"] != batch or e["page_size"] != page_size:
                continue
            if head_layout is not None and e["head_layout"] != head_layout:
                continue
            d = abs(e["pages_per_seq"] - pages_per_seq)
            if best is None or d < best[0]:
                best = (d, e)
        if best is None:
            return None
        e = best[1]
        # clamp like the env path: a table tuned at more pages/seq than this
        # engine allocates must not index past the sequence page budget
        return (max(1, min(pages_per_seq, int(e["bkv"]))), max(1, int(e["bq"])))


def _validate_entry(e) -> "dict | None":
    if not isinstance(e, dict):
        return None
    out = {}
    for k in _REQUIRED_INT_FIELDS:
        v = e.get(k)
        if isinstance(v, bool) or not isinstance(v, int) or v < 1:
            return None
        out[k] = v
    hl = e.get("head_layout")
    if not isinstance(hl, str) or not hl:
        return None
    out["head_layout"] = hl
    # carry optional provenance fields through merges untouched
    for k in ("us_per_call", "tuned_on", "tuned_at"):
        if k in e:
            out[k] = e[k]
    return out


def entries_hash(entries) -> str:
    """Order-independent short hash over the shape→winner mapping (provenance
    fields included so a re-tune with identical winners still changes hash)."""
    canon = sorted(json.dumps(e, sort_keys=True) for e in entries)
    return hashlib.sha256("\n".join(canon).encode()).hexdigest()[:12]


def load_table(path: str) -> "AttnTuneTable | None":
    """Parse + validate a tune file. Returns None (with a warning) on any
    file-level problem; drops malformed entries individually."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except FileNotFoundError:
        log.warning("attn tune file %s not found; using block-size heuristic", path)
        return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        log.warning("attn tune file %s unreadable (%s); using block-size "
                    "heuristic", path, e)
        return None
    if not isinstance(raw, dict) or raw.get("version") != 1 \
            or not isinstance(raw.get("entries"), list):
        log.warning("attn tune file %s has unknown schema; using block-size "
                    "heuristic", path)
        return None
    valid, dropped = [], 0
    for e in raw["entries"]:
        v = _validate_entry(e)
        if v is None:
            dropped += 1
        else:
            valid.append(v)
    if dropped:
        log.warning("attn tune file %s: dropped %d malformed entries", path, dropped)
    return AttnTuneTable(entries=tuple(valid), source=path,
                         sha=entries_hash(valid), dropped=dropped)


def merge_and_save(path: str, new_entries) -> AttnTuneTable:
    """bench.py's export: merge winners into an existing table file (same
    shape key → newest wins) and write it back atomically. Returns the merged
    table so the caller can report its hash."""
    existing = load_table(path) if os.path.exists(path) else None
    def key(e):
        return (e["batch"], e["page_size"], e["pages_per_seq"], e["head_layout"])
    merged = {key(e): e for e in (existing.entries if existing else ())}
    for e in new_entries:
        v = _validate_entry(e)
        if v is None:
            raise ValueError(f"refusing to write malformed tune entry: {e!r}")
        merged[key(v)] = v
    entries = [merged[k] for k in sorted(merged)]
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return AttnTuneTable(entries=tuple(entries), source=path,
                         sha=entries_hash(entries))


# ---------------------------------------------------------------------------
# process-wide active table, consulted by pick_block_sizes
# ---------------------------------------------------------------------------
_active: "AttnTuneTable | None" = None
_pinned = False  # explicit activate() beats env resolution
_resolved_env_path: "str | None" = None  # last env path resolved (cache key)


def activate(table: "AttnTuneTable | None") -> None:
    """Pin a table (engine startup with an explicit `attn_tune_file`).
    activate(None) unpins and returns control to env-var resolution."""
    global _active, _pinned, _resolved_env_path
    _active = table
    _pinned = table is not None
    _resolved_env_path = object()  # force re-resolution once unpinned


def active_table() -> "AttnTuneTable | None":
    """The table pick_block_sizes consults. An explicitly activate()d table
    wins; otherwise ``LLMD_ATTN_TUNE_FILE`` is resolved lazily and re-resolved
    whenever the env var changes (tests and the bench tuner set it
    mid-process)."""
    global _active, _resolved_env_path
    if _pinned:
        return _active
    env_path = os.environ.get(ENV_TUNE_FILE) or None
    if env_path != _resolved_env_path:
        _resolved_env_path = env_path
        _active = load_table(env_path) if env_path else None
    return _active


def active_hash() -> "str | None":
    t = active_table()
    return t.sha if t else None
