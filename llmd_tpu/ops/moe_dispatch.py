"""Token-sorted, drop-free MoE dispatch — the TPU answer to DeepEP's
low-latency all-to-all (SURVEY §2.4/§3.3, wide-ep decode.yaml
`--enable-dbo` / NVSHMEM buffers; ROADMAP item 1).

The legacy path in ``models.transformer.moe_block`` materialises dense
one-hot dispatch/combine tensors of shape ``[T, S, C]`` and pays
O(T·S·C·D) in the two routing einsums — at decode shapes that dwarfs the
expert GEMMs themselves, and any token routed past capacity ``C`` is
silently dropped. This module replaces it:

* argsort the flat ``(token, k)`` assignments by physical slot id
  (EPLB's replica choice already happened upstream, so the sort key IS
  the load-balanced placement),
* scatter activations into a block-aligned buffer whose per-slot
  segments start at multiples of the GEMM block size ``bc`` — static
  shapes, data-dependent fill, zero drops,
* run experts as a ragged grouped GEMM over the blocks (Pallas on TPU,
  gathered batched einsum on CPU/int8),
* combine by the inverse permutation, weighted by router probs.

Single device / ``ep == 1``: pure gather/scatter by sorted index, no
collective. ``ep > 1``: bounded per-rank buckets exchanged with
``lax.all_to_all`` inside ``shard_map`` — each EP rank owns a static
``1/ep`` slice of the token range, sends every routed copy to the rank
owning its slot (capacity = all of a rank's copies, so nothing can
drop), computes local experts token-sorted, and returns results over the
same buckets. ``jax.lax.ragged_all_to_all`` (jax >= 0.5) is
feature-detected and deliberately not required: the pinned jax 0.4.37
predates it, so the bounded-bucket exchange is the portable layout.

DBO: callers split the batch in half and invoke this path per half; the
two halves share no intermediate values, so half A's all-to-all is
data-independent of half B's expert GEMMs and XLA's scheduler may
overlap them. Each stage runs under a ``jax.named_scope`` (visible in
profiles) and is exported standalone so the engine's sampled phase probe
can time dispatch/experts/combine separately.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .grouped_gemm import ragged_grouped_gemm


def has_ragged_all_to_all() -> bool:
    """Newer jax ships a dedicated ragged collective; the pinned 0.4.37
    does not — the bounded-bucket ``all_to_all`` below is the fallback."""
    return hasattr(jax.lax, "ragged_all_to_all")


def pick_block_size(tokens_k: int, slots: int, pallas: bool) -> int:
    """GEMM block rows: about one slot's expected share, power of two.

    The padded buffer is ``Tk + S*bc`` rows, so small ``bc`` keeps the
    drop-free layout near-dense at decode shapes (Tk ~ S) while prefill
    (Tk >> S) gets MXU-sized blocks. Pallas tiles need >= 8 sublanes.
    """
    bc = 1
    while bc * slots < tokens_k and bc < 128:
        bc *= 2
    return max(8, bc) if pallas else bc


def _row_plan(slot: jax.Array, S: int, bc: int):
    """Static-shape placement of N routed copies into a block-aligned
    buffer. ``slot`` is [N] int32 in [0, S]; S is the padding sentinel.

    Returns (row [N], block_slot [nb], block_rows [nb], Tp): ``row[i]``
    is entry i's row in the padded buffer (== Tp for sentinels, which a
    mode="drop" scatter discards); block b holds rows of expert slot
    ``block_slot[b]`` with ``block_rows[b]`` of them real.
    """
    N = slot.shape[0]
    order = jnp.argsort(slot, stable=True)
    ss = slot[order]
    cnt = jnp.zeros((S + 1,), jnp.int32).at[slot].add(1)[:S]
    cnt_pad = ((cnt + bc - 1) // bc) * bc
    starts = jnp.cumsum(cnt) - cnt            # raw sorted-order starts
    starts_pad = jnp.cumsum(cnt_pad) - cnt_pad  # block-aligned starts
    Tp = ((N + bc - 1) // bc + S) * bc        # worst-case padding, static
    sc = jnp.minimum(ss, S - 1)
    pos_in_slot = jnp.arange(N, dtype=jnp.int32) - starts[sc]
    row_sorted = jnp.where(ss < S, starts_pad[sc] + pos_in_slot, Tp)
    row = jnp.zeros((N,), jnp.int32).at[order].set(row_sorted)
    nb = Tp // bc
    bstart = jnp.arange(nb, dtype=jnp.int32) * bc
    # segments are bc-aligned, so each block belongs to exactly one slot:
    # the last one whose padded start is <= the block start
    block_slot = jnp.clip(
        jnp.searchsorted(starts_pad, bstart, side="right").astype(jnp.int32) - 1,
        0, S - 1)
    block_rows = jnp.clip(starts_pad[block_slot] + cnt[block_slot] - bstart,
                          0, bc)
    return row, block_slot, block_rows, Tp


def _experts_xla(xb, block_slot, block_rows, wi, wo, wi_scale, wo_scale):
    """Gathered batched-einsum expert MLP over [nb, bc, D] blocks — the
    CPU / int8 backend. Dead rows are zero in ``xb`` and silu(0)*0 == 0,
    so no masking is needed; per-slot int8 scales gather with the bank."""
    dt = xb.dtype
    gate_up = jnp.einsum("bcd,bdf->bcf", xb, wi[block_slot].astype(dt))
    if wi_scale is not None:
        gate_up = gate_up * wi_scale[block_slot][:, None, :].astype(dt)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    ye = jnp.einsum("bcf,bfd->bcd", jax.nn.silu(gate) * up,
                    wo[block_slot].astype(dt))
    if wo_scale is not None:
        ye = ye * wo_scale[block_slot][:, None, :].astype(dt)
    return ye


def _experts_pallas(xb, block_slot, block_rows, wi, wo, wi_scale, wo_scale,
                    interpret):
    """Pallas ragged grouped GEMM backend (bf16 banks; int8 stays on the
    XLA path, mirroring the engine's einsum-path policy)."""
    gate_up = ragged_grouped_gemm(xb, wi, block_slot, block_rows,
                                  interpret=interpret)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    ye = ragged_grouped_gemm(jax.nn.silu(gate) * up, wo, block_slot,
                             block_rows, interpret=interpret)
    return ye


# --------------------------------------------------------------------------
# Stage functions (standalone so the engine phase probe can time each)
# --------------------------------------------------------------------------


def dispatch_stage(x, idx, topw, valid, S: int, bc: int):
    """Sort + scatter: flat (token, k) copies into the block buffer."""
    T, D = x.shape
    k = idx.shape[1]
    slot = jnp.where(valid > 0, idx, S).reshape(T * k)
    row, block_slot, block_rows, Tp = _row_plan(slot, S, bc)
    tok = (jnp.arange(T * k, dtype=jnp.int32) // k)
    xs = jnp.zeros((Tp, D), x.dtype).at[row].set(x[tok], mode="drop")
    wf = jnp.where(slot < S, topw.reshape(T * k), 0).astype(x.dtype)
    return xs, row, tok, wf, block_slot, block_rows


def experts_stage(xs, block_slot, block_rows, wi, wo, wi_scale=None,
                  wo_scale=None, *, use_pallas: bool = False,
                  interpret: Optional[bool] = None):
    """Per-block expert MLP on the sorted buffer: [Tp, D] -> [Tp, D]."""
    Tp, D = xs.shape
    bc = Tp // block_slot.shape[0]
    xb = xs.reshape(-1, bc, D)
    if use_pallas and wi_scale is None:
        ye = _experts_pallas(xb, block_slot, block_rows, wi, wo, wi_scale,
                             wo_scale, interpret)
    else:
        ye = _experts_xla(xb, block_slot, block_rows, wi, wo, wi_scale,
                          wo_scale)
    return ye.reshape(Tp, D)


def combine_stage(ye, row, tok, wf, T: int):
    """Inverse permutation + router-prob weighting back to [T, D]."""
    Tp, D = ye.shape
    g = ye[jnp.minimum(row, Tp - 1)]
    return jnp.zeros((T, D), ye.dtype).at[tok].add(g * wf[:, None])


def sorted_moe_local(x, idx, topw, valid, wi, wo, wi_scale=None,
                     wo_scale=None, *, use_pallas: bool = False,
                     interpret: Optional[bool] = None,
                     bc: Optional[int] = None):
    """Single-shard token-sorted MoE: gather/scatter only, no collective."""
    T, D = x.shape
    S = wi.shape[0]
    if bc is None:
        bc = pick_block_size(T * idx.shape[1], S, use_pallas and wi_scale is None)
    with jax.named_scope("moe_dispatch"):
        xs, row, tok, wf, block_slot, block_rows = dispatch_stage(
            x, idx, topw, valid, S, bc)
    with jax.named_scope("moe_experts"):
        ye = experts_stage(xs, block_slot, block_rows, wi, wo, wi_scale,
                           wo_scale, use_pallas=use_pallas, interpret=interpret)
    with jax.named_scope("moe_combine"):
        return combine_stage(ye, row, tok, wf, T)


# --------------------------------------------------------------------------
# Wide-EP path: bounded per-rank buckets over lax.all_to_all in shard_map
# --------------------------------------------------------------------------


def _sorted_rows(xr, lslot, Sl, bc, wi_l, wo_l, wis_l, wos_l, use_pallas,
                 interpret):
    """Receiver-side expert compute: rows already expanded per copy, one
    local slot id each. Output row i corresponds to input row i."""
    n, D = xr.shape
    row, block_slot, block_rows, Tp = _row_plan(lslot, Sl, bc)
    xs = jnp.zeros((Tp, D), xr.dtype).at[row].set(xr, mode="drop")
    ye = experts_stage(xs, block_slot, block_rows, wi_l, wo_l, wis_l, wos_l,
                       use_pallas=use_pallas, interpret=interpret)
    return ye[jnp.minimum(row, Tp - 1)]


def _ep_moe_body(xl, idxl, wl, vl, wi_l, wo_l, wis_l, wos_l, *, ep: int,
                 S: int, k: int, use_pallas: bool, interpret):
    """Per-device body under shard_map. ``xl`` is this (dp, sp) cell's
    token shard (replicated across ep/tp); ``wi_l`` holds the ``S/ep``
    expert slots this EP rank owns.

    DeepEP-analog exchange: rank r owns the r-th static 1/ep slice of the
    token range. Every routed copy of an owned token is bucketed by the
    rank owning its slot (bucket capacity = ALL of a rank's copies, so the
    exchange is drop-free by construction), shipped with one
    ``all_to_all``, computed token-sorted on the owner, and shipped back
    over the same buckets. Weighting/combine stay at the origin rank.
    """
    tl, D = xl.shape
    Sl = wi_l.shape[0]
    r = lax.axis_index("ep")
    if ep == 1:
        return sorted_moe_local(xl, idxl, wl, vl, wi_l, wo_l, wis_l, wos_l,
                                use_pallas=use_pallas, interpret=interpret)
    tpc = tl // ep  # caller pads: tl % ep == 0
    with jax.named_scope("moe_dispatch"):
        x_o = lax.dynamic_slice_in_dim(xl, r * tpc, tpc, 0)
        idx_o = lax.dynamic_slice_in_dim(idxl, r * tpc, tpc, 0)
        w_o = lax.dynamic_slice_in_dim(wl, r * tpc, tpc, 0)
        v_o = lax.dynamic_slice_in_dim(vl, r * tpc, tpc, 0)
        n = tpc * k
        cap = n  # bounded bucket: worst case all copies target one rank
        slot = jnp.where(v_o > 0, idx_o, S).reshape(n)
        dest = jnp.where(slot < S, slot // Sl, ep)  # sentinel: not sent
        order = jnp.argsort(dest, stable=True)
        dsort = dest[order]
        dcnt = jnp.zeros((ep + 1,), jnp.int32).at[dest].add(1)[:ep]
        dstart = jnp.cumsum(dcnt) - dcnt
        pos = jnp.arange(n, dtype=jnp.int32) - dstart[jnp.minimum(dsort, ep - 1)]
        sendrow = jnp.where(dsort < ep, dsort * cap + pos, ep * cap)
        entry_tok = (order // k).astype(jnp.int32)
        send_x = jnp.zeros((ep * cap, D), xl.dtype).at[sendrow].set(
            x_o[entry_tok], mode="drop").reshape(ep, cap, D)
        send_slot = jnp.full((ep * cap,), -1, jnp.int32).at[sendrow].set(
            slot[order], mode="drop").reshape(ep, cap)
        recv_x = lax.all_to_all(send_x, "ep", 0, 0, tiled=True)
        recv_slot = lax.all_to_all(send_slot, "ep", 0, 0, tiled=True)
    with jax.named_scope("moe_experts"):
        rs = recv_slot.reshape(ep * cap)
        lslot = jnp.where(rs >= 0, rs - r * Sl, Sl)  # -1 pad -> sentinel
        bc = pick_block_size(ep * cap, Sl, use_pallas and wis_l is None)
        ye = _sorted_rows(recv_x.reshape(ep * cap, D), lslot, Sl, bc,
                          wi_l, wo_l, wis_l, wos_l, use_pallas, interpret)
    with jax.named_scope("moe_combine"):
        back = lax.all_to_all(ye.reshape(ep, cap, D), "ep", 0, 0, tiled=True)
        outrow = back.reshape(ep * cap, D)
        g = outrow[jnp.minimum(sendrow, ep * cap - 1)]
        wf = (w_o.reshape(n)[order]
              * (dsort < ep).astype(xl.dtype)).astype(xl.dtype)
        y_o = jnp.zeros((tpc, D), xl.dtype).at[entry_tok].add(g * wf[:, None])
        return lax.all_gather(y_o, "ep", axis=0, tiled=True)  # [tl, D]


def make_sorted_dispatch(mesh=None, *, use_pallas: bool = False,
                         interpret: Optional[bool] = None):
    """Build a ``moe_block`` dispatch_impl closure.

    ``impl(x, idx, topw, valid, wi, wo, wi_scale, wo_scale) -> y``: the
    router / top-k / EPLB replica choice happened upstream (shared with
    the einsum path, so routing decisions are identical by construction);
    this only moves tokens, runs experts, and combines. With a mesh the
    body runs under shard_map over the full mesh — tokens split over
    (dp, sp), expert slots over ep (tp is gathered: wide-EP keeps expert
    banks EP-pure, matching the reference deployment) — and pads the
    token dim so every axis divides.
    """
    if mesh is None:
        def impl(x, idx, topw, valid, wi, wo, wi_scale=None, wo_scale=None):
            return sorted_moe_local(x, idx, topw, valid, wi, wo, wi_scale,
                                    wo_scale, use_pallas=use_pallas,
                                    interpret=interpret)
        return impl

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    shape = dict(mesh.shape)
    dpsp = shape.get("dp", 1) * shape.get("sp", 1)
    ep = shape.get("ep", 1)

    def impl(x, idx, topw, valid, wi, wo, wi_scale=None, wo_scale=None):
        T, D = x.shape
        k = idx.shape[1]
        S = wi.shape[0]
        mult = dpsp * ep
        Tp = ((T + mult - 1) // mult) * mult
        if Tp != T:
            pad = ((0, Tp - T),)
            x = jnp.pad(x, pad + ((0, 0),))
            idx = jnp.pad(idx, pad + ((0, 0),))
            topw = jnp.pad(topw, pad + ((0, 0),))
            valid = jnp.pad(valid, pad + ((0, 0),))  # pad rows invalid

        def body(xl, idxl, wl, vl, wi_l, wo_l, *scales):
            wis_l = scales[0] if wi_scale is not None else None
            wos_l = scales[1] if wi_scale is not None else None
            return _ep_moe_body(xl, idxl, wl, vl, wi_l, wo_l, wis_l, wos_l,
                                ep=ep, S=S, k=k, use_pallas=use_pallas,
                                interpret=interpret)

        tok = P(("dp", "sp"), None)
        in_specs = [tok, tok, tok, tok,
                    P("ep", None, None), P("ep", None, None)]
        args = [x, idx, topw, valid, wi, wo]
        if wi_scale is not None:
            in_specs += [P("ep", None), P("ep", None)]
            args += [wi_scale, wo_scale]
        y = shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                      out_specs=tok, check_rep=False)(*args)
        return y[:T]

    return impl
