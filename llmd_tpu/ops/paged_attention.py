"""Pallas ragged paged attention: the TPU-native answer to FlashInfer (SURVEY.md §2.5
N8, docker/Dockerfile.cuda:70-71).

Design (flash-decoding over a paged KV cache):
- grid ``(batch, kv_head)``; each program owns one sequence × one KV-head group and
  streams that sequence's pages HBM→VMEM with async DMA, ``pages_per_tile`` pages per
  iteration (tiles sized to the 128-lane MXU width),
- page indirection rides on **scalar prefetch**: the page table is available before
  the body runs, so DMA source addresses are computed in SMEM — no gather
  materialization of ``[B, S, Hk, Dh]`` in HBM (the reference-semantics fallback in
  ``models.transformer.paged_attention`` does exactly that gather; this kernel
  replaces it on TPU),
- online softmax (running max/sum) in fp32 VMEM scratch — single pass over KV, no
  ``[B, T, S]`` score materialization,
- tiles past ``kv_len`` are skipped entirely (``@pl.when``) — ragged batches pay for
  the KV they have, not the padded maximum,
- GQA: queries are regrouped to ``[B, Hk, T*q_per_kv, Dh]`` outside so each program's
  matmuls run over all queries sharing its KV head.

Decode (T=1) is HBM-bandwidth-bound: the win is streaming KV once at full bandwidth.
Prefill chunks (T=chunk) reuse the same kernel with more query rows per program.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _attn_kernel(
    # scalar prefetch
    pt_ref,  # [B, max_pages] int32 page table (SMEM)
    len_ref,  # [B] int32 kv lengths (SMEM)
    # inputs
    q_ref,  # [1, 1, R, Dh] queries for (b, kh), R = T * q_per_kv (VMEM)
    pos_ref,  # [1, R, 1] int32 query positions, -1 = padding (VMEM, column layout)
    k_hbm,  # [P, ps, Hk, Dh] key pages (stays in HBM)
    v_hbm,  # [P, ps, Hk, Dh] value pages (stays in HBM)
    # outputs
    o_ref,  # [1, 1, R, Dh] (VMEM)
    # scratch
    k_buf,  # [kv_tile, Dh] (VMEM)
    v_buf,  # [kv_tile, Dh] (VMEM)
    acc,  # [R, Dh] f32
    m_s,  # [R, 128] f32 running max (lane-replicated)
    l_s,  # [R, 128] f32 running sum (lane-replicated)
    sems,  # DMA sems [2, pages_per_tile]
    *,
    pages_per_tile: int,
    page_size: int,
    max_pages: int,
    scale: float,
):
    b = pl.program_id(0)
    kh = pl.program_id(1)
    kv_tile = pages_per_tile * page_size
    n_tiles = pl.cdiv(max_pages, pages_per_tile)
    kv_len = len_ref[b]

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [R, Dh]
    qpos_col = pos_ref[0]  # [R, 1] — column layout avoids 1D-vector relayouts
    R = q.shape[0]

    acc[:] = jnp.zeros_like(acc)
    m_s[:] = jnp.full_like(m_s, NEG_INF)
    l_s[:] = jnp.zeros_like(l_s)

    def tile_body(t, _):
        base = t * kv_tile

        @pl.when(base < kv_len)
        def _():
            # stage this tile's pages into contiguous VMEM (ragged → dense)
            for j in range(pages_per_tile):
                pidx = t * pages_per_tile + j
                page = jnp.where(pidx < max_pages, pt_ref[b, pidx], 0)
                page = jnp.maximum(page, 0)  # -1 (unmapped) → masked below
                pltpu.make_async_copy(
                    k_hbm.at[page, :, kh], k_buf.at[pl.ds(j * page_size, page_size), :],
                    sems.at[0, j],
                ).start()
                pltpu.make_async_copy(
                    v_hbm.at[page, :, kh], v_buf.at[pl.ds(j * page_size, page_size), :],
                    sems.at[1, j],
                ).start()
            for j in range(pages_per_tile):
                pltpu.make_async_copy(
                    k_hbm.at[0, :, kh], k_buf.at[pl.ds(j * page_size, page_size), :],
                    sems.at[0, j],
                ).wait()
                pltpu.make_async_copy(
                    v_hbm.at[0, :, kh], v_buf.at[pl.ds(j * page_size, page_size), :],
                    sems.at[1, j],
                ).wait()

            k = k_buf[:].astype(jnp.float32)  # [kv_tile, Dh]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [R, kv_tile]
            key_pos = base + jax.lax.broadcasted_iota(jnp.int32, (R, kv_tile), 1)
            mask = (key_pos < kv_len) & (key_pos <= qpos_col) & (qpos_col >= 0)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_s[:]  # [R, 128]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)  # [R, 128]
            p = jnp.exp(s - m_new[:, :1])  # [R, kv_tile]
            l_s[:] = l_s[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            m_s[:] = m_new
            pv = jax.lax.dot_general(
                p, v_buf[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [R, Dh]
            acc[:] = acc[:] * alpha[:, :1] + pv

        return 0

    jax.lax.fori_loop(0, n_tiles, tile_body, 0)
    l = jnp.maximum(l_s[:, :1], 1e-30)  # padding rows: l=0 → zeros, not NaN
    o_ref[0, 0] = (acc[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("kv_tile_target", "interpret"))
def paged_attention_pallas(
    q: jax.Array,  # [B, T, H, Dh]
    layer_cache: jax.Array,  # [2, P, ps, Hk, Dh]
    page_tables: jax.Array,  # [B, max_pages] int32 (-1 = unmapped)
    q_positions: jax.Array,  # [B, T] int32 global positions (-1 = padding)
    kv_lens: jax.Array,  # [B] int32 tokens resident incl. this step's
    kv_tile_target: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in replacement for models.transformer.paged_attention (same contract)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, T, H, Dh = q.shape
    _, P, ps, Hk, _ = layer_cache.shape
    qpk = H // Hk
    R = T * qpk
    max_pages = page_tables.shape[1]
    ppt = max(1, kv_tile_target // ps)
    kv_tile = ppt * ps

    # group queries by their KV head: [B, Hk, R, Dh], rows ordered (t, q-in-group)
    qg = q.reshape(B, T, Hk, qpk, Dh).transpose(0, 2, 1, 3, 4).reshape(B, Hk, R, Dh)
    pos = jnp.repeat(q_positions[:, :, None], qpk, axis=2).reshape(B, R, 1)
    kc, vc = layer_cache[0], layer_cache[1]

    kernel = functools.partial(
        _attn_kernel, pages_per_tile=ppt, page_size=ps, max_pages=max_pages,
        scale=Dh ** -0.5,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hk),
        in_specs=[
            pl.BlockSpec((1, 1, R, Dh), lambda b, kh, pt, kl: (b, kh, 0, 0)),
            pl.BlockSpec((1, R, 1), lambda b, kh, pt, kl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, R, Dh), lambda b, kh, pt, kl: (b, kh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kv_tile, Dh), layer_cache.dtype),
            pltpu.VMEM((kv_tile, Dh), layer_cache.dtype),
            pltpu.VMEM((R, Dh), jnp.float32),
            pltpu.VMEM((R, 128), jnp.float32),
            pltpu.VMEM((R, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, ppt)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hk, R, Dh), layer_cache.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32), qg, pos, kc, vc)
    return out.reshape(B, Hk, T, qpk, Dh).transpose(0, 2, 1, 3, 4).reshape(B, T, H, Dh)
