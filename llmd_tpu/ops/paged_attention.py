"""TPU ragged paged attention: the TPU-native answer to FlashInfer (SURVEY.md §2.5
N8, docker/Dockerfile.cuda:70-71).

The heavy lifting is the Pallas ragged-paged-attention kernel that ships with JAX
(`jax.experimental.pallas.ops.tpu.ragged_paged_attention` — the vLLM-TPU production
kernel): flash-decoding over a paged KV cache with double-buffered HBM→VMEM page
streaming, online softmax, and mixed prefill+decode in one flat token batch. This
module owns the serving-stack integration:

- the uniform attention-impl signature shared with the XLA-reference fallback
  (`models.transformer.ragged_paged_attention_xla`) so the engine can swap impls,
- **block-size selection**: the upstream tuned table has no entry for every
  (chip, shape) pair and its default (128 KV pages/block) is pathological for
  decode — measured on v5e (llama-1b shapes, B=32, kv_len 384): default blocks
  1,676 µs/layer vs 15-18 µs/layer with (bkv=8, bq=32). We clamp KV pages per
  block to the sequence page budget and keep it small,
- the VMEM budget (the kernel's scratch exceeds the 16 MB scoped-vmem default on
  larger head counts; vLLM-TPU ships 100 MB, we follow),
- the combined KV layout contract [P, page_size, 2*Hk, Dhp] (K even / V odd) with
  head_dim lane-padded — see `models.transformer.init_cache`.

Requires queries to be each sequence's LAST `q_len` tokens (true for chunked
prefill and decode — causality is derived as kv_len - q_len + local index).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from llmd_tpu.ops import attn_tune

VMEM_LIMIT = 100 * 1024 * 1024


@functools.cache
def _kernel():
    from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
        ragged_paged_attention as rpa,
    )

    return rpa


def pick_block_sizes(num_tokens: int, page_size: int, pages_per_seq: int,
                     *, head_layout: "str | None" = None) -> tuple[int, int]:
    """(num_kv_pages_per_block, num_queries_per_block) for our serving shapes.

    Resolution order, weakest to strongest:

    1. **heuristic** — KV blocks sized ~128 tokens keep decode DMAs overlapped
       without predicating past short sequences (v5e sweep above); q blocks of
       32 cover a full decode batch row budget per program, 64+ for big
       prefill batches,
    2. **auto-tune table** (`ops.attn_tune`, loaded from
       ``LLMD_ATTN_TUNE_FILE`` / `EngineConfig.attn_tune_file`) — bench.py's
       on-chip tuner's per-(batch, page_size, head layout) winners; an exact
       batch match replaces the heuristic, so b128 and long-context shapes
       stop running block sizes swept at b32,
    3. ``LLMD_ATTN_BKV`` / ``LLMD_ATTN_BQ`` env overrides — the operator
       escape hatch (and the legacy single-shape tuner export), applied at
       decode-gate shapes only (see deploy/ENV_VARS.md).
    """
    import os

    bkv = max(1, min(pages_per_seq, max(1, 128 // page_size)))
    bq = 32 if num_tokens <= 512 else 64
    table = attn_tune.active_table()
    if table is not None:
        # exact (batch, page_size, head_layout) key; nearest pages_per_seq —
        # non-tuned shapes (e.g. prefill token budgets) miss and keep policy
        hit = table.lookup(num_tokens, page_size, pages_per_seq, head_layout)
        if hit is not None:
            bkv, bq = hit
    try:
        decode_n = int(os.environ.get("LLMD_ATTN_DECODE_N", "128"))
    except ValueError:
        decode_n = 128
    if num_tokens <= decode_n:
        # overrides are tuned at the DECODE shape (one query per sequence,
        # num_tokens == batch); the tuner exports that batch size as
        # LLMD_ATTN_DECODE_N so the gate tracks the shape it validated.
        # Token batches above it — prefill budgets — keep the swept policy
        # (short tail chunks below the gate share the decode policy; a
        # perf-only approximation on the rare last chunk of a prompt).
        def _env_int(name: str):
            raw = os.environ.get(name)
            if not raw:
                return None
            try:
                return int(raw)
            except ValueError:
                return None  # malformed operator input: keep the policy

        env_bkv = _env_int("LLMD_ATTN_BKV")
        env_bq = _env_int("LLMD_ATTN_BQ")
        if env_bkv:
            bkv = max(1, min(pages_per_seq, env_bkv))
        if env_bq:
            bq = max(1, env_bq)
    return bkv, min(bq, num_tokens)


def paged_attention_tpu(
    q: jax.Array,  # [N, H, Dhp] flat query tokens (lane-padded)
    layer_cache: jax.Array,  # [P, ps, 2*Hk, Dhp]
    page_tables: jax.Array,  # [B, max_pages]
    positions: jax.Array,  # [N] (unused — causality derives from kv/cu lens)
    seq_slots: jax.Array,  # [N] (unused on this path)
    kv_lens: jax.Array,  # [B] tokens resident incl. this step's
    *,
    scale: float,
    cu_q_lens: jax.Array,  # [B+1] cumulative query lengths
    num_seqs: jax.Array,  # [1]
    chunk_k: "jax.Array | None" = None,  # unused (ring-attn impls only)
    chunk_v: "jax.Array | None" = None,  # unused (ring-attn impls only)
) -> jax.Array:
    """Uniform-signature adapter over the Pallas kernel (drop-in for
    models.transformer.ragged_paged_attention_xla on TPU)."""
    del positions, seq_slots, chunk_k, chunk_v
    N = q.shape[0]
    _, ps, planes, _ = layer_cache.shape
    bkv, bq = pick_block_sizes(
        N, ps, page_tables.shape[1],
        head_layout=attn_tune.head_layout_key(q.shape[1], q.shape[2], planes))
    # -1 marks unmapped table entries in engine convention; the kernel's scalar-
    # prefetched DMA would read out of bounds — clamp to page 0 (never attended:
    # those entries lie at/past kv_len).
    page_tables = jnp.maximum(page_tables, 0)
    extra = {}
    if layer_cache.dtype == jnp.float8_e4m3fn:
        # fp8 pages: unit scales make the kernel dequantize each KV block in
        # VMEM right after the page DMA (write_kv stores at scale 1.0 — e4m3's
        # dynamic range covers K/V activations), halving the HBM KV stream.
        # Kernel precondition: combined heads % 4 == 0 (strided fp8 load
        # packing). True for llama-1b both padded (16) and packed (8); NOT for
        # tiny CI models with 2 combined heads — there the engine's smoke
        # compile fails and serving falls back to the XLA reference impl.
        extra = {"k_scale": 1.0, "v_scale": 1.0}
    return _kernel()(
        q,
        layer_cache,
        kv_lens.astype(jnp.int32),
        page_tables.astype(jnp.int32),
        cu_q_lens.astype(jnp.int32),
        num_seqs.astype(jnp.int32),
        sm_scale=scale,
        num_kv_pages_per_block=bkv,
        num_queries_per_block=bq,
        vmem_limit_bytes=VMEM_LIMIT,
        **extra,
    )
