"""Serving throughput benchmark on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...provenance}.

Measures steady-state output token throughput (the reference's headline unit — output
tok/s, e.g. BASELINE.md rows 5/7/13) of the flagship single-chip model under
continuous batching: 64 concurrent requests, ISL 256 / OSL 128, greedy,
batched-across-sequences chunked prefill + multi-step fused decode.

Weights: ``--model <hf-dir>`` serves a real HF checkpoint through the full
safetensors load path (tests/test_hf_loader.py proves logits parity of that path
against the HF reference). With no flag, ``checkpoints/llama-1b-hf`` is used when
present (materialise with tools/make_checkpoint.py — genuine HF format, locally
generated: this zero-egress image cannot download published weights), else the
registry shape is random-initialised. The JSON records which.

vs_baseline anchors to BASELINE.md row 5: ~3,100 output tok/s per decode GPU
(16x16 B200 wide-EP) — the reference's per-accelerator decode throughput headline.

Per-phase breakdown (VERDICT r3 directive #3): the JSON decomposes wall time into
host-pack / device-step / post-process / launch-gap and prefill/decode wall split,
so the bandwidth-utilization gap is attributable, not guessed at.

Usage: python bench.py [--tiny] [--cpu] [--model DIR] [--batch N] [--decode-steps K]
                       [--isl N] [--osl N] [--quantize int8|none|default]
(default quantization is int8 on the standard serving run — measured 1.22x over
bf16 at batch 64; pass --quantize none for the bf16 measurement)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


# BASELINE.md row 5: ~3,100 output tok/s per decode GPU (16x16 B200 wide-EP),
# the reference's per-accelerator decode-throughput headline. The ONE anchor
# for both vs_baseline fields.
B200_ANCHOR_TOK_S = 3100.0


# Roofline math is shared with the live utilization plane (PR 17): one
# source of truth in obs/costmodel.py for params, per-token FLOPs/bytes and
# the device-generation peak table, so the offline decode_mfu here and the
# live llmd_tpu:program_mfu gauge can never drift apart.
from llmd_tpu.obs.costmodel import (  # noqa: E402
    GOODPUT_KINDS,
    bytes_per_param as _bytes_per_param,
    chip_peaks as _shared_chip_peaks,
    flops_per_token as _flops_per_token,
    param_count as _param_count,
)


def _device_preflight(attempts: int = 2, wait_s: float = 20.0,
                      timeout_s: float = 120.0) -> str | None:
    # 2x120s + 20s ≈ 4.3 min worst case: a healthy backend answers in <40s,
    # and the harvested-artifact fallback must still print within whatever
    # timeout the DRIVER runs bench.py under (r04's 3x180s preflight risked
    # eating the entire budget before the structured skip could be emitted)
    """Probe TPU backend init in a SUBPROCESS, with bounded retries + backoff.

    r04 lost its only hardware number to a transient backend-init UNAVAILABLE
    (rc=1 before any engine code ran), and ``jax.devices()`` has been observed
    to hang >120 s when the fabric is down — so the probe runs out-of-process
    (a hang or failure cannot poison this process's cached backend state) under
    a hard timeout. Returns None once a device answers, else the last error
    string so the caller can emit a structured device-unavailable JSON with
    rc=0 instead of dying.
    """
    import subprocess
    last = "unknown"
    for i in range(attempts):
        try:
            p = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
                capture_output=True, text=True, timeout=timeout_s)
            if p.returncode == 0:
                if i:
                    print(f"# device preflight recovered on attempt {i + 1}",
                          file=sys.stderr)
                return None
            lines = (p.stderr or p.stdout).strip().splitlines()
            last = lines[-1][:500] if lines else f"rc={p.returncode}"
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {timeout_s:.0f}s"
        print(f"# device preflight attempt {i + 1}/{attempts} failed: {last}",
              file=sys.stderr)
        if i + 1 < attempts:
            print(f"# retrying in {wait_s:.0f}s", file=sys.stderr)
            time.sleep(wait_s)
    return last


def _chip_peaks(device_kind: str) -> tuple[float, float]:
    """(bf16 TFLOP/s, HBM GB/s) from the shared costmodel peak table; bench
    keeps its historical off-table default (v5e-class) so CPU/unknown runs
    still print a roofline context instead of nulls."""
    return _shared_chip_peaks(device_kind, default=(197.0, 819.0))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true", help="CI-sized smoke run")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--model", default=None,
                    help="HF checkpoint dir (real-weight run) or registry name")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--decode-steps", type=int, default=None)
    ap.add_argument("--isl", type=int, default=None)
    ap.add_argument("--osl", type=int, default=None)
    ap.add_argument("--layer-unroll", type=int, default=None,
                    help="unroll the transformer layer scan N-wide "
                         "(LLMD_LAYER_UNROLL; lets XLA overlap next-layer "
                         "weight streams with compute)")
    ap.add_argument("--quantize", default="default",
                    choices=["int8", "none", "default"],
                    help="weight-only quantization (models/quant.py). "
                         "default: int8 on the standard serving run (decode "
                         "is weights-BW-bound; the reference baselines serve "
                         "fp8 — see PERF.md), off for --tiny; the bf16 "
                         "fallback config is unaffected either way")
    ap.add_argument("--kv-dtype", default="default",
                    choices=["fp8", "bf16", "default"],
                    help="KV-cache pool dtype (EngineConfig.kv_cache_dtype): "
                         "fp8 halves decode's per-step KV read stream — the "
                         "second HBM stream after weights at serving batch. "
                         "default: bf16 — MEASURED SLOWER as fp8 on v5e "
                         "(2,732 vs 4,042 tok/s at int8-b64): no native fp8 "
                         "datapath, so the VMEM dequant costs more than the "
                         "DMA bytes it saves; kept for fp8-native TPUs (v7x)")
    ap.add_argument("--kv-layout", default="auto",
                    choices=["auto", "packed", "padded"],
                    help="KV pool lane layout (ops/packed_kv): auto packs "
                         "f=Dhp/head_dim real KV heads per 128-lane row on "
                         "eligible models (llama-1b: f=2, halves KV bytes "
                         "again); padded forces one head per row (A/B)")
    ap.add_argument("--tune-attn", action="store_true",
                    help="force the attention block-size auto-tuner even off-"
                         "TPU (interpreter/XLA timings are meaningless there, "
                         "but the candidate sweep, tune-file merge, and engine "
                         "load path are the real code — ci_gate's "
                         "bench-tiny-attn stage pins the round trip) and "
                         "assert the engine loaded the exported table")
    ap.add_argument("--attn-tune-file", default=None,
                    help="tune-table path (ops/attn_tune JSON) the tuner "
                         "merges winners into; default: LLMD_ATTN_TUNE_FILE, "
                         "else attn_tune.json next to bench.py (a temp file "
                         "under --tune-attn so CI runs don't pollute the tree)")
    ap.add_argument("--spec-mode", default="off", choices=["off", "ngram"],
                    help="speculative decoding: ngram = prompt-lookup drafts "
                         "verified through the mixed-batch step (one verify "
                         "step can land several output tokens; greedy "
                         "acceptance keeps output bitwise identical)")
    ap.add_argument("--spec-tokens", type=int, default=None,
                    help="max draft tokens per sequence per verify step "
                         "(default: EngineConfig default)")
    ap.add_argument("--attn-impl", default="auto",
                    choices=["auto", "pallas", "reference"],
                    help="attention kernel selection (EngineConfig.attn_impl): "
                         "auto = Pallas on TPU / XLA reference elsewhere; "
                         "pallas forces the Pallas kernels (MLA decode takes "
                         "the latent-width kernel); reference forces the XLA "
                         "gather+mask path — the pallas-vs-xla A/B lever")
    ap.add_argument("--pack-overlap", default="on", choices=["on", "off"],
                    help="chained decode dispatches reuse the in-flight "
                         "call's device-resident tokens/positions/kv-lens "
                         "(EngineConfig.pack_overlap); off = legacy "
                         "serialized full pack — the Lever 12 A/B")
    ap.add_argument("--structured-fused", default="on", choices=["on", "off"],
                    help="constrained rows ride the fused masked decode "
                         "program (EngineConfig.structured_fused_decode); "
                         "off = 1-token unified degrade — the Lever 12 "
                         "structured A/B (pair with --workload json)")
    ap.add_argument("--chain-depth", type=int, default=None,
                    help="fused decode calls kept in flight per chain "
                         "(EngineConfig.pipeline_depth; default: config "
                         "default)")
    ap.add_argument("--workload", default="uniform",
                    choices=["uniform", "echo", "json", "json-echo"],
                    help="prompt distribution: uniform = distinct pseudo-random "
                         "streams (no lookup structure); echo = periodic "
                         "prompts whose continuations repeat — the shared-"
                         "prefix/agentic/summarization regime where prompt-"
                         "lookup acceptance is high; json = every request is "
                         "schema-constrained (response_format json_schema) — "
                         "prices the structured-outputs mask path end to end; "
                         "json-echo = echo prompts AND schema constraint — the "
                         "structured x speculative compose (Lever 13): "
                         "grammar-masked verify accepts drafts on constrained "
                         "rows (pair with --spec-mode ngram)")
    ap.add_argument("--moe-dispatch", default="auto",
                    choices=["auto", "sorted", "einsum"],
                    help="MoE token dispatch: sorted = token-sorted drop-free "
                         "path (ops/moe_dispatch), einsum = legacy capacity "
                         "dispatch (the parity reference, silently drops past "
                         "capacity); auto = sorted. Dense models ignore it — "
                         "the moe-sorted/moe-einsum campaign A/B lever")
    ap.add_argument("--assert-spec-structured", action="store_true",
                    help="fail unless constrained rows accepted >0 draft "
                         "tokens AND the run had 0 structured violations — "
                         "ci_gate's bench-tiny-spec-structured stage pins the "
                         "grammar-masked verify path end to end")
    args = ap.parse_args()
    tiny = args.tiny
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        err = _device_preflight()
        if err is not None:
            # rc=0 + structured skip: a flaky fabric must never erase a
            # round's number as an opaque crash (VERDICT r4 weak #1). If a
            # device window EARLIER in the round already measured the serving
            # default (tools/r05_campaign.py harvests into the campaign
            # artifact), the FLAG-DEFAULT invocation (the driver's
            # end-of-round `python bench.py`) reports that number with
            # explicit provenance instead of nothing. Any invocation with
            # explicit flags — every campaign point — still skips with a
            # null value: substituting the serving default's number for a
            # different requested config would fabricate a measurement, and
            # the campaign's run_point relabels rows by point name.
            out = {
                "metric": "output_tok_per_s_per_chip", "value": None,
                "unit": "tok/s", "vs_baseline": None,
                "skipped": "device-unavailable", "error": err,
            }
            flag_default = not tiny and args.model is None \
                and not any([args.batch, args.decode_steps, args.isl, args.osl,
                             args.layer_unroll]) \
                and os.environ.get("LLMD_LAYER_UNROLL") in (None, "", "1") \
                and args.quantize == "default" and args.kv_dtype == "default" \
                and args.kv_layout == "auto" and args.spec_mode == "off" \
                and args.spec_tokens is None and args.workload == "uniform" \
                and args.attn_impl == "auto" and args.pack_overlap == "on" \
                and args.structured_fused == "on" and args.chain_depth is None \
                and args.moe_dispatch == "auto"
            if flag_default:
                try:
                    import glob as _glob
                    import re as _re

                    # newest CANONICAL campaign artifact (round-agnostic —
                    # a stale filename constant would re-emit a prior round's
                    # number as this round's). Suffixed variants like
                    # *_preclamp.json are lever-attribution records of STALE
                    # code states; the strict pattern keeps them out.
                    camps = sorted(
                        (p for p in _glob.glob(os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_CAMPAIGN_r*.json"))
                         if _re.fullmatch(r"BENCH_CAMPAIGN_r\d+\.json",
                                          os.path.basename(p))),
                        # numeric round order: lexicographic sort would rank
                        # r9 above r10 and resurface a stale round's number
                        key=lambda p: int(_re.search(
                            r"r(\d+)", os.path.basename(p)).group(1)))
                    camp = camps[-1] if camps else ""
                    with open(camp) as f:
                        data = json.load(f)
                    best = data.get("best_serving") or {}
                    row = next((r for r in data.get("results", [])
                                if r.get("point") == best.get("point")
                                and r.get("value")), None)
                    if row:
                        out = dict(row)
                        out.pop("wall_total_s", None)
                        out["source"] = (
                            f"harvested on-chip from {os.path.basename(camp)} "
                            f"(campaign point {row['point']}); live device "
                            f"unavailable at bench time: {err}")
                except (OSError, json.JSONDecodeError, KeyError):
                    pass
            print(json.dumps(out))
            return
    import jax

    from llmd_tpu.core.request import SamplingParams
    from llmd_tpu.engine import EngineConfig, LLMEngine
    from llmd_tpu.models import resolve_model

    if tiny:
        model, n_req, isl, osl = "tiny", 8, 64, 32
        eng_cfg = EngineConfig(page_size=16, num_pages=256, max_model_len=512,
                               max_batch_size=8, prefill_chunk=64, decode_steps=8,
                               max_num_batched_tokens=256, instrument=True)
    else:
        model, n_req, isl, osl = "llama-1b", 64, 256, 128
        # Batch 64: decode is weights-BW-bound, so per-step time barely grows
        # with batch while tokens/step doubles — measured on-chip r05:
        # int8 b32 2,872 tok/s vs int8 b64 3,419 tok/s (BENCH_CAMPAIGN_r05_preclamp.json).
        # NT=8192 prefills the batch in two unified steps (one host round trip
        # each; ~67 ms tunnel RTT per call). decode_steps=32 halves fused-call
        # count for the same reason. bench falls back to the r03-proven config
        # if this one fails to build/serve (see build_and_measure fallback below).
        eng_cfg = EngineConfig(page_size=16, num_pages=2048, max_model_len=1024,
                               max_batch_size=64, prefill_chunk=256, decode_steps=32,
                               max_num_batched_tokens=8192, instrument=True)
        default_ckpt = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "checkpoints", "llama-1b-hf")
        if args.model is None and os.path.isfile(os.path.join(default_ckpt, "config.json")):
            args.model = default_ckpt
    if args.model is not None:
        model = args.model
    n_req = args.batch or n_req
    isl, osl = args.isl or isl, args.osl or osl
    if args.batch:
        eng_cfg.max_batch_size = args.batch
        eng_cfg.max_num_batched_tokens = max(eng_cfg.batched_tokens, args.batch * 8)
    if args.decode_steps:
        eng_cfg.decode_steps = args.decode_steps
    if args.layer_unroll:
        os.environ["LLMD_LAYER_UNROLL"] = str(args.layer_unroll)
    quantize_explicit = args.quantize != "default"
    if args.quantize == "default":
        args.quantize = None if tiny else "int8"
    elif args.quantize == "none":
        args.quantize = None
    eng_cfg.quantize_weights = args.quantize
    kv_explicit = args.kv_dtype != "default" or args.kv_layout != "auto"
    eng_cfg.kv_cache_dtype = "fp8" if args.kv_dtype == "fp8" else None
    eng_cfg.kv_layout = args.kv_layout
    spec_explicit = (args.spec_mode != "off" or args.spec_tokens is not None
                     or args.workload != "uniform")
    eng_cfg.spec_mode = args.spec_mode
    if args.spec_tokens is not None:
        eng_cfg.spec_tokens = args.spec_tokens
    chain_explicit = (args.attn_impl != "auto" or args.pack_overlap != "on"
                      or args.structured_fused != "on"
                      or args.chain_depth is not None)
    moe_explicit = args.moe_dispatch != "auto"
    eng_cfg.moe_dispatch = args.moe_dispatch
    eng_cfg.attn_impl = args.attn_impl
    eng_cfg.pack_overlap = args.pack_overlap == "on"
    eng_cfg.structured_fused_decode = args.structured_fused == "on"
    if args.chain_depth is not None:
        eng_cfg.pipeline_depth = max(1, args.chain_depth)
    # host↔device round-trip (PCIe locally; tens of ms through the dev tunnel) —
    # the latency the pipelined decode path exists to hide
    import jax.numpy as jnp
    import numpy as _np

    _f = jax.jit(lambda x: x + 1)
    _np.asarray(_f(jnp.zeros(())))
    t0 = time.monotonic()
    for _ in range(3):
        _np.asarray(_f(jnp.zeros(())))
    rtt_ms = (time.monotonic() - t0) / 3 * 1e3
    print(f"# host<->device RTT {rtt_ms:.1f} ms", file=sys.stderr)

    t0 = time.monotonic()
    cfg, params = resolve_model(model)
    from llmd_tpu.models.transformer import layer_unroll as _layer_unroll_fn

    # same parse + clamp as the trace site, so the artifact records exactly
    # the unroll width that ran (env is the source of truth; the flag sets it)
    _layer_unroll_prov = _layer_unroll_fn(cfg.num_layers)
    weights_src = f"hf:{model}" if params is not None else f"random:{model}"
    load_s = time.monotonic() - t0
    print(f"# weights {weights_src} (loaded in {load_s:.1f}s)", file=sys.stderr)

    # json workload: every request is schema-constrained. The schema is fully
    # bounded (enum/boolean/maxLength — a DAG grammar), so the mask forces
    # completion; ignore_eos then keeps emitting EOS from the terminal state
    # to fill osl, keeping token counts comparable across workloads. The
    # longest serialization is 29 chars, under the tiny smoke's osl=32 —
    # truncating a constrained row would count a violation per request.
    bench_schema = {
        "type": "object",
        "properties": {"n": {"type": "string", "maxLength": 4},
                       "c": {"enum": [0, 1, 2, 3, 4, 5, 6, 7]},
                       "ok": {"type": "boolean"}},
        "required": ["n", "c", "ok"],
    }
    if args.workload == "json-echo":
        # constrained-echo: a fixed-count array of identical single-enum
        # objects serializes to a fully-forced PERIODIC string
        # ('[{"s":"on"},{"s":"on"},...]', period 11 chars) — after the first
        # element the prompt-lookup drafter reads every next element from the
        # sequence's own output, and the grammar-masked verify program
        # accepts whole drafts (the structured analogue of the echo
        # workload's repeated spans; the reference regime is agentic tool
        # loops re-emitting near-identical JSON). Element count scales with
        # osl so the echo body, not the EOS tail, dominates the measurement.
        n_items = max(1, (osl - 10) // 11)
        bench_schema = {
            "type": "array",
            "items": {"type": "object", "properties": {"s": {"enum": ["on"]}},
                      "required": ["s"]},
            "minItems": n_items, "maxItems": n_items,
        }

    def _sampling() -> SamplingParams:
        kw = dict(max_tokens=osl, temperature=0.0, ignore_eos=True)
        if args.workload.startswith("json"):
            kw["response_format"] = {"type": "json_schema",
                                     "json_schema": {"schema": bench_schema}}
        return SamplingParams(**kw)

    sp = _sampling()

    def prompts(n: int, salt: int, tok=None):
        if args.workload == "json-echo" and tok is not None:
            # the constrained-echo regime proper: the prompt carries the
            # forced serialization pattern the output will repeat (an
            # agentic tool loop re-emitting JSON it saw in context), so
            # prompt-lookup drafts fire from the first generated token
            # instead of waiting for the output's own first element. A
            # salted head keeps prompts distinct (no prefix-cache shortcut).
            pat = tok.encode('[{"s":"on"},' + '{"s":"on"},' * 3)
            out = []
            for i in range(n):
                head = [(salt * 7919 + i * 131 + j) % (cfg.vocab_size - 2) + 1
                        for j in range(4)]
                body = (pat * (isl // max(1, len(pat)) + 1))[: isl - len(head)]
                out.append(head + body)
            return out
        if args.workload in ("echo", "json-echo"):
            # echo-heavy: each prompt is a short per-request pattern repeated
            # to ISL (still distinct across requests — no prefix-cache
            # shortcut), so the continuation repeats spans of the context —
            # the regime where prompt-lookup drafting pays
            period = 3
            return [[(salt * 7919 + i * 131 + j % period) % (cfg.vocab_size - 2) + 1
                     for j in range(isl)] for i in range(n)]
        # distinct prompts (no prefix-cache shortcut): salt offsets the token stream
        return [[(salt * 7919 + i * 131 + j) % (cfg.vocab_size - 2) + 1 for j in range(isl)]
                for i in range(n)]

    def build_and_measure(run_cfg):
        """Size KV pool for the config, build, warm up, run the measured window."""
        # +decode_steps*(depth+1): the pipelined fused-decode path pre-allocates
        # lookahead slots for every in-flight call; undersizing silently
        # degrades every step to the unified fallback
        lookahead = run_cfg.decode_steps * (run_cfg.pipeline_depth + 1)
        pages_per_seq = (isl + osl + lookahead) // run_cfg.page_size + 1
        run_cfg.num_pages = max(run_cfg.num_pages, n_req * pages_per_seq + 64)
        run_cfg.max_model_len = max(run_cfg.max_model_len, isl + osl + lookahead + 1)
        t0 = time.monotonic()
        tok = None
        if args.workload.startswith("json"):
            from llmd_tpu.engine.tokenizer import load_tokenizer

            # HF checkpoints carry their tokenizer; random weights mask over
            # the byte fallback (same vocab the prompt generator draws from)
            tok = load_tokenizer(model if params is not None else None)
        eng = LLMEngine(cfg, run_cfg, params=params, tokenizer=tok)
        dev = jax.devices()[0]
        print(f"# engine built in {time.monotonic() - t0:.1f}s on {dev} "
              f"(NT={run_cfg.batched_tokens}, k={run_cfg.decode_steps})",
              file=sys.stderr)
        print(f"# attn_backend={eng.attn_backend}"
              + (f" (fallback: {eng.attn_fallback_reason})" if eng.attn_fallback_reason else "")
              + (f" tune={eng.attn_tune_hash}" if eng.attn_tune_hash else ""),
              file=sys.stderr)
        print(f"# moe_backend={eng.moe_backend} moe_dispatch={eng.moe_dispatch}"
              + (f" (fallback: {eng.moe_dispatch_fallback_reason})"
                 if eng.moe_dispatch_fallback_reason else ""),
              file=sys.stderr)
        t0 = time.monotonic()
        eng.generate(prompts(2, salt=1, tok=tok), _sampling())
        print(f"# warmup/compile {time.monotonic() - t0:.1f}s", file=sys.stderr)
        # fresh stats for the measured window (every counter zeroed by construction)
        from llmd_tpu.engine.engine import EngineStats

        eng.stats = EngineStats(attn_backend=eng.stats.attn_backend,
                                attn_tune_hash=eng.stats.attn_tune_hash,
                                moe_backend=eng.stats.moe_backend,
                                moe_dispatch=eng.stats.moe_dispatch,
                                kv_cache_dtype=eng.stats.kv_cache_dtype,
                                kv_layout=eng.stats.kv_layout)
        # utilization-ledger baseline: registry counters can't reset, so the
        # goodput/recompile provenance keys report measured-window DELTAS
        # against this post-warmup snapshot (matching the stats reset above)
        eng.util_bench_base = (
            (eng.util.totals(), eng.util.compiles(), eng.util.moe_comm_total())
            if eng.util is not None else None)
        t0 = time.monotonic()
        out = eng.generate(prompts(n_req, salt=2, tok=tok), sp)
        return eng, out, time.monotonic() - t0

    def tune_attention() -> "str | None":
        """Time candidate attention block sizes at the decode shape and export
        the winner two ways: the legacy LLMD_ATTN_BKV/BQ env override and a
        shape-keyed entry merged into the tune table (ops/attn_tune), which
        the engine loads via LLMD_ATTN_TUNE_FILE — so a campaign accumulates
        per-(batch, page_size, head layout) winners instead of one global
        answer tuned at whatever batch ran last. Kernel ablation showed
        attention at 4.4 ms/step vs a ~0.9 ms KV-read roofline — the single
        largest per-step cost — and the original default (bkv=8, bq=32) was
        chosen with broken timing (block_until_ready is a no-op through the
        tunnel). Returns the merged table's hash, or None if nothing ran.

        Candidates route through the REAL serving impl (paged_attention_tpu,
        packed-wrapped when serving packs) with the candidate applied via the
        env overrides and a fresh trace per candidate — the measurement
        includes the adapter and slot-placement overheads serving pays.
        Off-TPU (--tune-attn only) the impl is the XLA reference: timings are
        meaningless there (block sizes never reach the XLA path) but the
        sweep, tune-file merge, env export, and engine load are the same
        code — ci_gate's bench-tiny-attn stage pins that round trip on CPU.
        Wholly best-effort on-chip: any failure keeps the defaults."""
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu and not args.tune_attn:
            return None
        if cfg.is_mla:
            # the latent decode kernel (ops/mla_decode) streams one page per
            # grid step — it has no block-size knobs to tune
            print("# attn-tune: MLA latent decode has no block-size knobs; "
                  "skipping", file=sys.stderr)
            return None
        import numpy as _np

        from llmd_tpu.models.transformer import (
            padded_head_dim, ragged_paged_attention_xla)
        from llmd_tpu.ops import attn_tune as _attn_tune

        B = eng_cfg.max_batch_size
        ps = eng_cfg.page_size
        kvlen = isl + osl // 2
        maxp = (isl + osl + eng_cfg.decode_steps * 3) // ps + 1
        npages = max(1024, B * maxp) if on_tpu else B * maxp + 8
        Hk = max(1, cfg.num_kv_heads)
        Dhp = padded_head_dim(cfg.head_dim)
        pack = 1
        if eng_cfg.kv_layout != "padded":
            from llmd_tpu.ops.packed_kv import pack_factor
            pack = pack_factor(cfg)
        planes = 2 * Hk // pack
        cache = jnp.zeros((npages, ps, planes, Dhp), jnp.bfloat16)
        pts = _np.zeros((B, maxp), _np.int32)
        for i in range(B):
            pts[i] = (_np.arange(i * maxp, (i + 1) * maxp)) % npages
        pts = jnp.asarray(pts)
        kv_lens = jnp.full((B,), kvlen, jnp.int32)
        pos0 = jnp.full((B,), kvlen - 1, jnp.int32)
        slots0 = jnp.arange(B, dtype=jnp.int32)
        cu = jnp.asarray(_np.arange(B + 1), jnp.int32)
        ns = jnp.asarray([B], jnp.int32)
        q0 = jnp.ones((B, cfg.num_heads, Dhp), jnp.bfloat16)
        if on_tpu:
            from llmd_tpu.ops.paged_attention import paged_attention_tpu
            impl = paged_attention_tpu
        else:
            impl = ragged_paged_attention_xla
        if pack > 1:
            from llmd_tpu.ops.packed_kv import make_packed_attn
            impl = make_packed_attn(impl, cfg, pack)
        scan_len = 16 if on_tpu else 2
        _ENV = ("LLMD_ATTN_BKV", "LLMD_ATTN_BQ", "LLMD_ATTN_DECODE_N")

        def timed(bkv: int, bq: int) -> float:
            import jax.lax as lax
            saved = {k: os.environ.get(k) for k in _ENV}
            os.environ.update(LLMD_ATTN_BKV=str(bkv), LLMD_ATTN_BQ=str(bq),
                              LLMD_ATTN_DECODE_N=str(B))
            try:
                def f(q):
                    def body(qq, _):
                        o = impl(qq, cache, pts, pos0, slots0, kv_lens,
                                 scale=0.125, cu_q_lens=cu, num_seqs=ns)
                        return (o * 1e-3 + qq * 0.999).astype(qq.dtype), None
                    qq, _ = lax.scan(body, q, None, length=scan_len)
                    return jnp.sum(qq.astype(jnp.float32))
                # fresh closure => fresh trace per candidate: the env override
                # is read at trace time inside pick_block_sizes
                jf = jax.jit(f)
                _np.asarray(jax.device_get(jf(q0)))  # compile + settle
                # FRESH input per measured call: the tunneled runtime
                # content-caches identical (executable, args) pairs — re-timing
                # q0 would measure the cache, not the kernel. Multipliers must
                # be EXACTLY representable in bf16 (1.001 rounds to 1.0 —
                # spacing near 1.0 is 1/128 — which would reproduce q0 bitwise
                # and hit the cache). min-of-2 damps per-dispatch RTT jitter.
                times = []
                for rep in (1.0078125, 1.015625):  # 1+1/128, 1+2/128: exact in bf16
                    t0 = time.monotonic()
                    _np.asarray(jax.device_get(jf(q0 * jnp.bfloat16(rep))))
                    times.append(time.monotonic() - t0)
                return min(times)
            finally:
                for k, v in saved.items():
                    os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)

        candidates = [(8, 32), (max(1, maxp // 2), 32), (maxp, 32), (8, 16)]
        default = candidates[0]
        results: dict = {}
        for bkv, bq in candidates:
            try:
                results[(bkv, bq)] = timed(bkv, bq)
                print(f"# attn-tune bkv={bkv} bq={bq}: "
                      f"{results[(bkv, bq)]*1e3:.1f} ms/{scan_len}calls",
                      file=sys.stderr)
            except Exception:
                continue
        if default not in results or not results:
            return None
        best = min(results, key=results.get)
        # a non-default winner must beat the default by a real margin —
        # residual RTT jitter must not flip the policy
        if best != default and results[best] >= 0.95 * results[default]:
            best = default
        if best != default:
            os.environ["LLMD_ATTN_BKV"] = str(best[0])
            os.environ["LLMD_ATTN_BQ"] = str(best[1])
            # gate tracks the exact batch the candidates were timed at —
            # without it a --batch 256 run would tune, export, and then
            # silently never apply the overrides (default gate is 128)
            os.environ["LLMD_ATTN_DECODE_N"] = str(B)
            print(f"# attn-tune picked bkv={best[0]} bq={best[1]} "
                  f"(decode_n={B})", file=sys.stderr)
        # the winner ALWAYS lands in the table (even when it is the default:
        # a timed win at this shape beats re-deriving the heuristic later)
        path = args.attn_tune_file or os.environ.get("LLMD_ATTN_TUNE_FILE")
        if not path:
            here = os.path.dirname(os.path.abspath(__file__))
            if args.tune_attn:
                import tempfile
                path = os.path.join(tempfile.gettempdir(),
                                    f"llmd_attn_tune_{os.getpid()}.json")
            else:
                path = os.path.join(here, "attn_tune.json")
        entry = {
            "batch": B, "page_size": ps, "pages_per_seq": maxp,
            "head_layout": _attn_tune.head_layout_key(cfg.num_heads, Dhp, planes),
            "bkv": best[0], "bq": best[1],
            "us_per_call": round(results[best] / scan_len * 1e6, 1),
            "tuned_on": getattr(jax.devices()[0], "device_kind",
                                jax.default_backend()),
        }
        table = _attn_tune.merge_and_save(path, [entry])
        os.environ["LLMD_ATTN_TUNE_FILE"] = path
        print(f"# attn-tune table {path} sha={table.sha} "
              f"({len(table.entries)} entries)", file=sys.stderr)
        return table.sha

    attn_tune_sha = None
    if not tiny or args.tune_attn:
        try:
            attn_tune_sha = tune_attention()
        except Exception as e:  # tuning must never cost the bench run
            if args.tune_attn:
                raise  # ...except when the round trip IS the point (ci_gate)
            print(f"# attn-tune skipped ({type(e).__name__}: {e})", file=sys.stderr)

    primary_error = None
    try:
        eng, out, wall = build_and_measure(eng_cfg)
    except Exception as e:
        # the r04 defaults are more aggressive (single-step prefill, k=32);
        # a bench run must never die to a config experiment — fall back to the
        # r03-proven shape and measure that instead
        if (tiny or args.batch or args.decode_steps or args.isl or args.osl
                or args.layer_unroll or quantize_explicit or kv_explicit
                or spec_explicit or chain_explicit or moe_explicit):
            # an explicitly requested shape or quantization must not silently
            # re-measure as something else (e.g. bf16 under an "int8" label)
            raise
        # record and DROP the exception: its traceback pins the failed
        # engine's device buffers alive, which would make an OOM-triggered
        # fallback hit the same OOM
        primary_error = f"{type(e).__name__}: {e}"
    if primary_error is not None:
        print(f"# WARNING: primary config failed ({primary_error}); "
              "falling back to NT=2048/k=16", file=sys.stderr)
        # only non-explicit runs reach here (explicit flags re-raise above), so
        # the fallback is always the r03-proven bf16 shape — the safety net must
        # not share a failure mode with the int8 default it is rescuing, and the
        # rescue measurement must match the r03 protocol (32 requests, one wave)
        # kv_layout pinned to the r03-proven padded layout: the safety net
        # must not rebuild the auto-packed program it may be rescuing from
        eng_cfg = EngineConfig(page_size=16, num_pages=2048, max_model_len=1024,
                               max_batch_size=32, prefill_chunk=256, decode_steps=16,
                               max_num_batched_tokens=2048, instrument=True,
                               kv_layout="padded")
        n_req = min(n_req, 32)
        eng, out, wall = build_and_measure(eng_cfg)
    dev = jax.devices()[0]
    if args.tune_attn and attn_tune_sha is not None:
        # the round-trip gate: the engine must have loaded the exact table the
        # tuner just exported (same short hash) — a silent miss here is the
        # "tuned but never applied" failure mode this machinery replaces
        assert eng.attn_tune_hash == attn_tune_sha, (
            "engine did not load the tuner's exported table",
            eng.attn_tune_hash, attn_tune_sha)
        print(f"# attn-tune round trip OK (engine loaded sha={attn_tune_sha})",
              file=sys.stderr)
    out_tokens = sum(len(v) for v in out.values())
    assert out_tokens == n_req * osl, (out_tokens, n_req * osl)
    tput = out_tokens / wall
    if args.assert_spec_structured:
        # Lever 13 gate: the grammar-masked verify program must have landed
        # real draft acceptances on constrained rows without a single
        # conformance violation — a silent fallback to per-step decode would
        # pass a plain throughput check while the lever is dead
        st_ = eng.stats
        assert st_.spec_accepted_constrained > 0, (
            "no accepted drafts on constrained rows",
            st_.spec_drafted_constrained, st_.spec_accepted_constrained)
        assert st_.structured_violations == 0, (
            "constrained-spec run produced violations",
            st_.structured_violations)
        assert st_.spec_fsm_crosscheck_mismatches == 0, (
            st_.spec_fsm_crosscheck_mismatches)

    # --- provenance / roofline context -------------------------------------
    st = eng.stats
    n_params = _param_count(cfg)
    # int8 weight-only serves ~1 byte/param for the dense per-step stream
    # (scales are per-channel, negligible); the weights-BW estimate must use
    # the bytes actually read or utilization overstates 2x
    bytes_per_param = _bytes_per_param(cfg, eng_cfg.quantize_weights)
    peak_tflops, peak_gbs = _chip_peaks(getattr(dev, "device_kind", ""))
    # decode reads all weights once per step for max_batch_size tokens
    model_gb = n_params * bytes_per_param / 1e9
    hbm_gb_per_tok = model_gb / max(1, eng_cfg.max_batch_size)
    achieved_gbs = tput * hbm_gb_per_tok  # weights-traffic-only lower bound
    # decode-phase-only rate: the apples-to-apples number against BASELINE.md
    # row 5 (the B200 anchor is a DECODE-pod rate in wide-EP disagg — its
    # prefill runs elsewhere); the headline above stays conservative by
    # including our prefill in the denominator. Numerator counts only tokens
    # from fused decode calls — the unified-step degrade path produces decode
    # tokens whose wall time lands in time_prefill_steps.
    decode_tput = st.decode_tokens_fused / max(1e-9, st.time_decode_steps)
    decode_bw_gbs = decode_tput * hbm_gb_per_tok
    flops_per_tok = _flops_per_token(cfg)
    mfu = tput * flops_per_tok / (peak_tflops * 1e12)
    launch_gap = (wall - st.time_prefill_steps - st.time_decode_steps
                  - st.time_spec_steps)
    dev_ms_per_decode = (st.time_device_decode / max(1, st.n_decode_calls)) * 1e3
    pack_us_per_call = (
        st.time_host_pack / max(1, st.n_decode_calls + st.n_unified_steps)) * 1e6
    # token-goodput + recompile provenance over the measured window (deltas
    # against the post-warmup ledger snapshot; None with LLMD_UTIL_LEDGER off)
    goodput = {k: None for k in GOODPUT_KINDS}
    padding_efficiency = recompiles = moe_comm_bytes = None
    if eng.util is not None and getattr(eng, "util_bench_base", None) is not None:
        base_tokens, base_compiles, base_moe_comm = eng.util_bench_base
        # measured-window MoE all-to-all traffic (same accumulator that
        # feeds program_mbu, so ledger == scrape by construction)
        moe_comm_bytes = round(eng.util.moe_comm_total() - base_moe_comm)
        goodput = {k: 0 for k in GOODPUT_KINDS}
        for prog_name, tk in eng.util.totals().items():
            base = base_tokens.get(prog_name, {})
            for kind, v in tk.items():
                goodput[kind] += v - base.get(kind, 0)
        real = (goodput["committed"] + goodput["spec_rejected"]
                + goodput["preempted_recompute"])
        cap = real + goodput["padding"]
        padding_efficiency = round(real / cap, 4) if cap else None
        recompiles = sum(v - base_compiles.get(p, 0)
                         for p, v in eng.util.compiles().items())

    print(f"# {out_tokens} output tokens in {wall:.2f}s "
          f"(prefill {st.total_prefill_tokens} toks, "
          f"decode {st.total_decode_tokens} toks, "
          f"preemptions {st.total_preemptions})", file=sys.stderr)
    if st.structured_requests:
        print(f"# structured: {st.structured_requests} constrained requests, "
              f"{st.structured_mask_builds} mask builds + "
              f"{st.structured_chain_stages} chain stages in "
              f"{st.time_mask_build:.3f}s host, "
              f"violations {st.structured_violations}",
              file=sys.stderr)
    if st.n_spec_verify_steps:
        print(f"# spec: drafted {st.spec_drafted}, accepted {st.spec_accepted}, "
              f"rejected {st.spec_rejected} over {st.n_spec_verify_steps} verify "
              f"steps ({st.spec_accepted / st.n_spec_verify_steps:.2f} "
              f"accepted/verify-step; constrained "
              f"{st.spec_accepted_constrained}/{st.spec_drafted_constrained} "
              "accepted/drafted)", file=sys.stderr)
    print(f"# phase split: prefill-steps {st.time_prefill_steps:.2f}s, "
          f"decode-steps {st.time_decode_steps:.2f}s, "
          f"spec-steps {st.time_spec_steps:.2f}s, launch-gap {launch_gap:.2f}s | "
          f"host-pack {st.time_host_pack:.2f}s serialized "
          f"(+{st.time_pack_overlap:.2f}s overlapped, "
          f"{st.n_chained_dispatches} chained dispatches), "
          f"device {st.time_device:.2f}s, "
          f"post {st.time_postprocess:.2f}s "
          f"({st.n_unified_steps} unified + {st.n_decode_calls} decode calls; "
          f"{dev_ms_per_decode:.1f} ms device/decode-call)", file=sys.stderr)
    wdtype = "int8" if eng_cfg.quantize_weights == "int8" else cfg.dtype
    print(f"# model {n_params/1e9:.2f}B params ({model_gb:.2f} GB {wdtype}); "
          f"weights-BW {achieved_gbs:.0f} GB/s of ~{peak_gbs:.0f} peak "
          f"({achieved_gbs/peak_gbs*100:.0f}%); decode-MFU {mfu*100:.2f}%",
          file=sys.stderr)

    print(json.dumps({
        "metric": "output_tok_per_s_per_chip",
        "value": round(tput, 1),
        "unit": "tok/s",
        "vs_baseline": round(tput / B200_ANCHOR_TOK_S, 4),
        "weights": weights_src,
        "quantize": eng_cfg.quantize_weights,
        "kv_cache_dtype": eng.stats.kv_cache_dtype,
        "kv_layout": eng.stats.kv_layout,
        "attn_backend": eng.attn_backend,
        "attn_fallback_reason": eng.attn_fallback_reason,
        "attn_tune_hash": eng.attn_tune_hash,
        "moe_backend": eng.moe_backend,
        "moe_dispatch": eng.moe_dispatch,
        "moe_dropped_tokens": eng.stats.moe_dropped_tokens,
        "moe_comm_bytes": moe_comm_bytes,
        "device": getattr(dev, "device_kind", str(dev)),
        "weights_bw_gbs": round(achieved_gbs, 1),
        "weights_bw_util": round(achieved_gbs / peak_gbs, 3),
        "decode_tok_per_s": round(decode_tput, 1),
        "decode_vs_baseline": round(decode_tput / B200_ANCHOR_TOK_S, 4),
        "decode_weights_bw_util": round(decode_bw_gbs / peak_gbs, 3),
        "decode_mfu": round(mfu, 4),
        "prefill_tokens": st.total_prefill_tokens,
        "decode_tokens": st.total_decode_tokens,
        "preemptions": st.total_preemptions,
        # utilization plane (obs/costmodel.py): slot-token fate over the
        # measured window — counters exact run-to-run for a fixed workload
        "goodput_committed_tokens": goodput["committed"],
        "goodput_spec_rejected_tokens": goodput["spec_rejected"],
        "goodput_padding_tokens": goodput["padding"],
        "goodput_preempted_recompute_tokens": goodput["preempted_recompute"],
        "goodput_prefix_saved_tokens": goodput["prefix_saved"],
        "padding_efficiency": padding_efficiency,
        "recompiles": recompiles,
        # per-phase wall breakdown (seconds over the measured run)
        "wall_s": round(wall, 3),
        "prefill_steps_s": round(st.time_prefill_steps, 3),
        "decode_steps_s": round(st.time_decode_steps, 3),
        "spec_steps_s": round(st.time_spec_steps, 3),
        "launch_gap_s": round(launch_gap, 3),
        "host_pack_s": round(st.time_host_pack, 3),
        # Lever 12 (device-resident decode): pack wall hidden behind the
        # in-flight chain, and the serialized per-step host total the lever
        # shrinks (time_host_pack + time_mask_build) — A/B vs --pack-overlap
        # off / --structured-fused off
        "pack_overlap_s": round(st.time_pack_overlap, 3),
        "chained_dispatches": st.n_chained_dispatches,
        "serialized_host_s": round(st.time_host_pack + st.time_mask_build, 4),
        "pack_overlap": eng_cfg.pack_overlap,
        "structured_fused": eng_cfg.structured_fused_decode,
        "chain_depth": eng_cfg.pipeline_depth,
        "attn_impl": eng_cfg.attn_impl,
        "device_s": round(st.time_device, 3),
        "device_decode_s": round(st.time_device_decode, 3),
        "postprocess_s": round(st.time_postprocess, 3),
        "unified_steps": st.n_unified_steps,
        "decode_calls": st.n_decode_calls,
        "device_ms_per_decode_call": round(dev_ms_per_decode, 2),
        "host_pack_us_per_call": round(pack_us_per_call, 1),
        "host_device_rtt_ms": round(rtt_ms, 1),
        "pipeline_decode": eng_cfg.pipeline_decode,
        "layer_unroll": _layer_unroll_prov,
        "batch": eng_cfg.max_batch_size,
        "decode_steps_fused": eng_cfg.decode_steps,
        "isl": isl,
        "osl": osl,
        "workload": args.workload,
        "spec_mode": eng_cfg.spec_mode,
        "spec_tokens": eng_cfg.spec_tokens if eng_cfg.spec_mode != "off" else None,
        "spec_drafted": st.spec_drafted,
        "spec_accepted": st.spec_accepted,
        "spec_rejected": st.spec_rejected,
        # Lever 13 (structured x speculative): drafted/accepted on grammar- or
        # logit_bias-constrained rows — the grammar-masked verify program's
        # contribution, zero before this lever existed
        "spec_drafted_constrained": st.spec_drafted_constrained,
        "spec_accepted_constrained": st.spec_accepted_constrained,
        "spec_fsm_crosscheck_mismatches": st.spec_fsm_crosscheck_mismatches,
        "spec_verify_steps": st.n_spec_verify_steps,
        "spec_accepted_per_verify_step": round(
            st.spec_accepted / st.n_spec_verify_steps, 3)
        if st.n_spec_verify_steps else None,
        # structured-outputs provenance (--workload json): the host mask-build
        # wall is the feature's per-step cost — compare against device_s
        "structured_requests": st.structured_requests,
        "structured_mask_builds": st.structured_mask_builds,
        "structured_chain_stages": st.structured_chain_stages,
        "structured_violations": st.structured_violations,
        "mask_build_s": round(st.time_mask_build, 4),
    }))


if __name__ == "__main__":
    main()
