"""Serving throughput benchmark on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...provenance}.

Measures steady-state output token throughput (the reference's headline unit — output
tok/s, e.g. BASELINE.md rows 5/7/13) of the flagship single-chip model (llama-1b,
random weights) under continuous batching: 32 concurrent requests, ISL 256 / OSL 128,
greedy, batched-across-sequences chunked prefill + multi-step fused decode.

vs_baseline anchors to BASELINE.md row 5: ~3,100 output tok/s per decode GPU
(16x16 B200 wide-EP) — the reference's per-accelerator decode throughput headline.
A v5e chip has ~1/20 the FLOPs/HBM-BW of a B200, so >0.1 here already means the
serving stack itself (batching, paging, fused decode) is not the bottleneck.

Kernel provenance (VERDICT r1 'What's weak' #2): the JSON records which attention /
MoE implementation actually served the run and why any fallback fired, plus achieved
model-bandwidth and MFU estimates, so the number is diagnosable.

Usage: python bench.py [--tiny] [--cpu]   (flags for CI-sized smoke runs)
"""

from __future__ import annotations

import json
import sys
import time


def _param_count(cfg) -> int:
    D, L, F = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size
    H, Hk, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    per_layer = D * (H + 2 * Hk) * Dh + H * Dh * D + 3 * D * F  # qkvo + swiglu
    emb = cfg.vocab_size * D * (1 if cfg.tie_embeddings else 2)
    return per_layer * L + emb


def _chip_peaks(device_kind: str) -> tuple[float, float]:
    """(bf16 TFLOP/s, HBM GB/s) for MFU / bandwidth-utilization estimates."""
    kinds = {
        "TPU v5 lite": (197.0, 819.0),
        "TPU v5e": (197.0, 819.0),
        "TPU v5p": (459.0, 2765.0),
        "TPU v4": (275.0, 1228.0),
        "TPU v6e": (918.0, 1640.0),
    }
    for k, v in kinds.items():
        if k.lower() in device_kind.lower():
            return v
    return (197.0, 819.0)  # default to v5e-class


def main() -> None:
    tiny = "--tiny" in sys.argv
    if "--cpu" in sys.argv:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from llmd_tpu.core.request import SamplingParams
    from llmd_tpu.engine import EngineConfig, LLMEngine
    from llmd_tpu.models import get_model_config

    if tiny:
        model, n_req, isl, osl = "tiny", 8, 64, 32
        eng_cfg = EngineConfig(page_size=16, num_pages=256, max_model_len=512,
                               max_batch_size=8, prefill_chunk=64, decode_steps=8,
                               max_num_batched_tokens=256)
    else:
        model, n_req, isl, osl = "llama-1b", 32, 256, 128
        eng_cfg = EngineConfig(page_size=16, num_pages=2048, max_model_len=1024,
                               max_batch_size=32, prefill_chunk=256, decode_steps=16,
                               max_num_batched_tokens=2048)

    cfg = get_model_config(model)
    t0 = time.monotonic()
    eng = LLMEngine(cfg, eng_cfg)
    dev = jax.devices()[0]
    print(f"# engine built in {time.monotonic() - t0:.1f}s on {dev}", file=sys.stderr)
    print(f"# attn_backend={eng.attn_backend}"
          + (f" (fallback: {eng.attn_fallback_reason})" if eng.attn_fallback_reason else ""),
          file=sys.stderr)
    print(f"# moe_backend={eng.moe_backend}", file=sys.stderr)

    sp = SamplingParams(max_tokens=osl, temperature=0.0, ignore_eos=True)

    def prompts(n: int, salt: int):
        # distinct prompts (no prefix-cache shortcut): salt offsets the token stream
        return [[(salt * 7919 + i * 131 + j) % (cfg.vocab_size - 2) + 1 for j in range(isl)]
                for i in range(n)]

    # Warmup: compile unified prefill + fused decode (and exercise the allocator)
    t0 = time.monotonic()
    eng.generate(prompts(2, salt=1), SamplingParams(max_tokens=osl, temperature=0.0, ignore_eos=True))
    print(f"# warmup/compile {time.monotonic() - t0:.1f}s", file=sys.stderr)

    t0 = time.monotonic()
    out = eng.generate(prompts(n_req, salt=2), sp)
    wall = time.monotonic() - t0
    out_tokens = sum(len(v) for v in out.values())
    assert out_tokens == n_req * osl, (out_tokens, n_req * osl)
    tput = out_tokens / wall

    # --- provenance / roofline context -------------------------------------
    n_params = _param_count(cfg)
    bytes_per_param = 2 if cfg.dtype == "bfloat16" else 4
    peak_tflops, peak_gbs = _chip_peaks(getattr(dev, "device_kind", ""))
    # decode reads all weights once per step for max_batch_size tokens
    model_gb = n_params * bytes_per_param / 1e9
    hbm_gb_per_tok = model_gb / max(1, eng_cfg.max_batch_size)
    achieved_gbs = tput * hbm_gb_per_tok  # weights-traffic-only lower bound
    flops_per_tok = 2 * n_params
    mfu = tput * flops_per_tok / (peak_tflops * 1e12)

    print(f"# {out_tokens} output tokens in {wall:.2f}s "
          f"(prefill {eng.stats.total_prefill_tokens} toks, "
          f"decode {eng.stats.total_decode_tokens} toks, "
          f"preemptions {eng.stats.total_preemptions})", file=sys.stderr)
    print(f"# model {n_params/1e9:.2f}B params ({model_gb:.2f} GB bf16); "
          f"weights-BW {achieved_gbs:.0f} GB/s of ~{peak_gbs:.0f} peak "
          f"({achieved_gbs/peak_gbs*100:.0f}%); decode-MFU {mfu*100:.2f}%",
          file=sys.stderr)

    print(json.dumps({
        "metric": "output_tok_per_s_per_chip",
        "value": round(tput, 1),
        "unit": "tok/s",
        "vs_baseline": round(tput / 3100.0, 4),
        "attn_backend": eng.attn_backend,
        "attn_fallback_reason": eng.attn_fallback_reason,
        "moe_backend": eng.moe_backend,
        "device": getattr(dev, "device_kind", str(dev)),
        "weights_bw_gbs": round(achieved_gbs, 1),
        "weights_bw_util": round(achieved_gbs / peak_gbs, 3),
        "decode_mfu": round(mfu, 4),
        "prefill_tokens": eng.stats.total_prefill_tokens,
        "decode_tokens": eng.stats.total_decode_tokens,
        "preemptions": eng.stats.total_preemptions,
    }))


if __name__ == "__main__":
    main()
