"""Serving throughput benchmark on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures steady-state output token throughput (the reference's headline unit — output
tok/s, e.g. BASELINE.md rows 5/7/13) of the flagship single-chip model (llama-1b,
random weights) under continuous batching: 32 concurrent requests, ISL 256 / OSL 128,
greedy, multi-step fused decode.

vs_baseline anchors to BASELINE.md row 5: ~3,100 output tok/s per decode GPU
(16x16 B200 wide-EP) — the reference's per-accelerator decode throughput headline.
A v5e chip has ~1/20 the FLOPs/HBM-BW of a B200, so >0.1 here already means the
serving stack itself (batching, paging, fused decode) is not the bottleneck.

Usage: python bench.py [--tiny] [--cpu]   (flags for CI-sized smoke runs)
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    tiny = "--tiny" in sys.argv
    if "--cpu" in sys.argv:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from llmd_tpu.core.request import SamplingParams
    from llmd_tpu.engine import EngineConfig, LLMEngine
    from llmd_tpu.models import get_model_config

    if tiny:
        model, n_req, isl, osl = "tiny", 8, 64, 32
        eng_cfg = EngineConfig(page_size=16, num_pages=256, max_model_len=512,
                               max_batch_size=8, prefill_chunk=64, decode_steps=8)
    else:
        model, n_req, isl, osl = "llama-1b", 32, 256, 128
        eng_cfg = EngineConfig(page_size=16, num_pages=2048, max_model_len=1024,
                               max_batch_size=32, prefill_chunk=256, decode_steps=16)

    cfg = get_model_config(model)
    t0 = time.monotonic()
    eng = LLMEngine(cfg, eng_cfg)
    print(f"# engine built in {time.monotonic() - t0:.1f}s on {jax.devices()[0]}", file=sys.stderr)

    sp = SamplingParams(max_tokens=osl, temperature=0.0, ignore_eos=True)

    def prompts(n: int, salt: int):
        # distinct prompts (no prefix-cache shortcut): salt offsets the token stream
        return [[(salt * 7919 + i * 131 + j) % (cfg.vocab_size - 2) + 1 for j in range(isl)]
                for i in range(n)]

    # Warmup: compile prefill + fused decode (and exercise the allocator)
    t0 = time.monotonic()
    eng.generate(prompts(2, salt=1), SamplingParams(max_tokens=osl, temperature=0.0, ignore_eos=True))
    print(f"# warmup/compile {time.monotonic() - t0:.1f}s", file=sys.stderr)

    t0 = time.monotonic()
    out = eng.generate(prompts(n_req, salt=2), sp)
    wall = time.monotonic() - t0
    out_tokens = sum(len(v) for v in out.values())
    assert out_tokens == n_req * osl, (out_tokens, n_req * osl)
    tput = out_tokens / wall
    print(f"# {out_tokens} output tokens in {wall:.2f}s "
          f"(prefill {eng.stats.total_prefill_tokens} toks, "
          f"decode {eng.stats.total_decode_tokens} toks, "
          f"preemptions {eng.stats.total_preemptions})", file=sys.stderr)

    print(json.dumps({
        "metric": "output_tok_per_s_per_chip",
        "value": round(tput, 1),
        "unit": "tok/s",
        "vs_baseline": round(tput / 3100.0, 4),
    }))


if __name__ == "__main__":
    main()
