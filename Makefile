# Developer/CI entry points (role of the reference's root Makefile, whose
# DEVICE matrix builds container images; ours gates the source tree).

PY ?= python

.PHONY: check check-quick test bench dryrun lint manifests chaos structured slo device-obs kvplane decisions durable perf-regress util moe pd

# full gate: lint + manifests + suite + tiny bench + 8-device dryrun
check:
	$(PY) tools/ci_gate.py

# PR-sized gate (fail-fast tests, 2-device dryrun)
check-quick:
	$(PY) tools/ci_gate.py --quick

test:
	$(PY) -m pytest tests/ -q

# full static-analysis suite: lock discipline, deadlock order, hot-path
# purity, env/metrics/events contracts (docs/static-analysis.md)
lint:
	$(PY) tools/lint_envvars.py
	$(PY) tools/lint_events.py
	JAX_PLATFORMS=cpu $(PY) tools/lint_metrics.py
	JAX_PLATFORMS=cpu $(PY) -m tools.llmd_lint

manifests:
	$(PY) tools/validate_manifests.py deploy

bench:
	$(PY) bench.py --tiny --cpu

# router resilience vs fault-injected endpoints (goodput >= 99%, no 5xx)
chaos:
	JAX_PLATFORMS=cpu $(PY) tools/chaos_check.py

# grammar-constrained decoding: 100% conformance, malformed schemas -> 400
structured:
	JAX_PLATFORMS=cpu $(PY) tools/structured_check.py

# autoscaling SLO gate: 10x burst + replica chaos, zero 5xx, warm 0->1
slo:
	JAX_PLATFORMS=cpu $(PY) tools/slo_check.py

# P/D disaggregation: role-labeled pools, predictor-gated splits, kv_pull
# ledgers, mid-burst prefill-pool kill degrades to aggregated, zero 5xx
pd:
	JAX_PLATFORMS=cpu $(PY) tools/pd_check.py

# device plane: watchdog, fabric probe, HBM gauges, profiler capture
device-obs:
	JAX_PLATFORMS=cpu $(PY) tools/device_obs_check.py

# global KV plane: precise routing + cross-engine pulls under churn, zero 5xx
kvplane:
	JAX_PLATFORMS=cpu $(PY) tools/kv_plane_check.py

# decision plane: per-request routing ledgers, predictor calibration,
# regret — 100% coverage over a replayed trace, zero 5xx
decisions:
	JAX_PLATFORMS=cpu $(PY) tools/decision_check.py

# durable prefix tier: write-back + store rung survive scale-to-zero and a
# mid-run store kill — five-rung token identity, zero 5xx
durable:
	JAX_PLATFORMS=cpu $(PY) tools/kv_durability_check.py

# utilization plane: per-program goodput sums to 1, MFU/MBU families on the
# null-peak path, recompile counter flat in steady state, ledger == /metrics
util:
	JAX_PLATFORMS=cpu $(PY) tools/util_check.py

# MoE dispatch plane: tiny-moe engine A/B — sorted path selected under auto,
# greedy parity vs the einsum reference, zero drops on sorted, provable drops
# on capacity-starved einsum, counter == engine ledger
moe:
	JAX_PLATFORMS=cpu $(PY) tools/moe_check.py

# perf contract: pinned campaign point vs pinned BENCH baseline under
# per-metric tolerances (tools/perf_regress.py --run gates a fresh bench)
perf-regress:
	$(PY) tools/perf_regress.py --candidate BENCH_CAMPAIGN_r05.json \
		--baseline BENCH_r05.json

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) __graft_entry__.py
