#!/usr/bin/env bash
# Provision the llmd-tpu observability kit (A9) into a cluster:
# - Grafana dashboards as labeled ConfigMaps (grafana sidecar auto-discovery)
# - Prometheus alert rules as a ConfigMap
#
# Required environment variables:
#  - NAMESPACE: target namespace for the ConfigMaps
#
# Usage:
#   NAMESPACE=llm-d-monitoring ./observability/install.sh            # apply
#   NAMESPACE=llm-d-monitoring ./observability/install.sh --dry-run  # render only
set -euo pipefail

HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
NAMESPACE="${NAMESPACE:?set NAMESPACE to the monitoring namespace}"
DRY_RUN="${1:-}"

apply() {
  if [[ "${DRY_RUN}" == "--dry-run" ]]; then
    cat
  else
    kubectl apply -n "${NAMESPACE}" -f -
  fi
}

for dash in "${HERE}"/grafana/*.json; do
  name="llmd-tpu-dash-$(basename "${dash}" .json)"
  kubectl create configmap "${name}" \
    --from-file="$(basename "${dash}")=${dash}" \
    --dry-run=client -o yaml \
    | kubectl label --local -f - grafana_dashboard=1 --dry-run=client -o yaml \
    | apply
done

kubectl create configmap llmd-tpu-alert-rules \
  --from-file="alerts.yaml=${HERE}/alerts.yaml" \
  --dry-run=client -o yaml \
  | kubectl label --local -f - prometheus_rules=1 --dry-run=client -o yaml \
  | apply

echo "observability kit: $(ls "${HERE}"/grafana/*.json | wc -l) dashboards + alert rules -> namespace ${NAMESPACE} ${DRY_RUN}"
